"""Candidate-list (KNN) proposals: structure, validity, and quality.

The KNN proposal (moves.knn_table / knn_src_map) is the SA quality
lever: second move endpoints come from the current node's nearest
neighbors, which measured ~19% lower best-cost on synth X-n200 at
identical routes/s. These tests pin the table structure, that proposals
remain valid permutation transforms in both eval modes, and that
candidate-list SA does not lose to uniform SA on a fixed seed.
"""

import numpy as np
import jax
import pytest

from vrpms_tpu.core.cost import CostWeights, objective_batch
from vrpms_tpu.core.encoding import is_valid_giant, random_giant_batch
from vrpms_tpu.io.synth import synth_cvrp
from vrpms_tpu.moves import knn_move_batch, knn_table
from vrpms_tpu.solvers import SAParams, solve_sa


class TestKnnTable:
    def test_nearest_first_and_no_self(self, rng):
        d = rng.uniform(1, 100, size=(12, 12))
        np.fill_diagonal(d, 0)
        knn = np.asarray(knn_table(d, 5))
        assert knn.shape == (12, 5)
        for a in range(12):
            assert a not in knn[a]
            dists = d[a, knn[a]]
            assert np.all(np.diff(dists) >= 0)  # sorted ascending
            # first entry is the true nearest non-self node
            others = np.delete(d[a], a)
            assert dists[0] == others.min()

    def test_width_clamped_to_n_minus_1(self, rng):
        d = rng.uniform(1, 10, size=(4, 4))
        assert knn_table(d, 16).shape == (4, 3)


class TestKnnMoves:
    @pytest.mark.parametrize("mode", ["gather", "onehot"])
    def test_moves_stay_valid_permutations(self, mode):
        inst = synth_cvrp(21, 4, seed=3)
        giants = random_giant_batch(jax.random.key(0), 32, 20, 4)
        knn = knn_table(inst.durations[0], 8)
        out = knn_move_batch(jax.random.key(1), giants, knn, mode=mode)
        for row in np.asarray(out):
            assert is_valid_giant(row, 20, 4)

    def test_modes_agree_exactly(self):
        inst = synth_cvrp(21, 4, seed=3)
        giants = random_giant_batch(jax.random.key(0), 32, 20, 4)
        knn = knn_table(inst.durations[0], 8)
        a = knn_move_batch(jax.random.key(2), giants, knn, mode="gather")
        b = knn_move_batch(jax.random.key(2), giants, knn, mode="onehot")
        assert np.array_equal(np.asarray(a), np.asarray(b))


class TestKnnQuality:
    def test_candidate_list_not_worse_than_uniform(self):
        inst = synth_cvrp(41, 6, seed=7)
        w = CostWeights.make()
        knn_res = solve_sa(
            inst, key=0, params=SAParams(n_chains=64, n_iters=1500, knn_k=10)
        )
        uni_res = solve_sa(
            inst, key=0, params=SAParams(n_chains=64, n_iters=1500, knn_k=0)
        )
        assert is_valid_giant(knn_res.giant, 40, 6)
        # identical budget and seed: the candidate list should not lose
        # (on synth instances it wins by a wide margin; allow equality)
        assert float(knn_res.cost) <= float(uni_res.cost) * 1.02
