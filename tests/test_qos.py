"""Deadline-aware QoS scheduling tests (ISSUE 12).

Layers:

  * TestQosUnits — class parsing/ranking, EDF order keys, tenant
    identity, the free-rider mate-selection rule, shed fractions;
  * TestLocalQueueQos — JobQueue with a QosPolicy attached: priority
    pop (class first, EDF within class, FIFO-stable ties — a
    randomized ordering property), selective-shed admission with
    per-class Retry-After from observed drain, the free-rider gather
    (same-class members never displaced), per-class depth;
  * TestStoreClaimQos — the shared in-memory queue store: claim and
    claim_batch honor the same ordering contract (randomized property
    against a reference sort), the batch fill prefers same-class mates
    with lower classes as free riders, qos-less entries stay pure
    FIFO, per-class/per-tenant depth maps, fleet-wide tenant
    accounting across two claiming owners;
  * TestStaleDeadlineFastFail — a claimed entry whose deadline budget
    was fully spent in queue wait dies at materialize with the clean
    "deadline exhausted" envelope (before any prepare/compile) and is
    counted in vrpms_jobs_shed_total{reason="deadline_exhausted"};
  * TestQosHTTP (slow) — the HTTP surface: selective shed (batch 429s
    while interactive still admits) with per-class Retry-After,
    per-tenant quota 429s (anonymous exempt), and the /api/ready qos
    block (per-class depth + tenant inflight map);
  * TestQosDistHTTP (slow) — the store-backed path: per-tenant quota
    enforced fleet-wide across two in-process replicas via shared
    store accounting;
  * TestQosOffGuard (slow) — VRPMS_QOS=off builds no policy, treats
    'qos' like any unknown key (junk does not 400), writes no
    claim-ordering fields, and serves fixed-seed responses identical
    to a qos-less request.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import store
import store.memory as mem
from service import jobs as jobs_mod
from service import obs as service_obs
from vrpms_tpu.sched import Job, JobQueue, Scheduler, qos
from vrpms_tpu.sched.batcher import gather_batch


@pytest.fixture(autouse=True)
def clean_store(monkeypatch):
    monkeypatch.setenv("VRPMS_STORE", "memory")
    monkeypatch.delenv("VRPMS_QUEUE", raising=False)
    monkeypatch.delenv("VRPMS_QOS", raising=False)  # default: on
    mem.reset()
    yield
    mem.reset()


def _job(cls="standard", deadline=None, bucket=None, tl=None):
    j = Job(payload={}, bucket=bucket, time_limit=tl)
    j.qos = cls
    j.deadline_at = deadline
    return j


# ---------------------------------------------------------------------------
# Units
# ---------------------------------------------------------------------------


class TestQosUnits:
    def test_parse_class(self):
        assert qos.parse_class(None) == "standard"
        assert qos.parse_class("Interactive") == "interactive"
        assert qos.parse_class(" batch ") == "batch"
        for junk in ("gold", 3, [], "inter active"):
            with pytest.raises(ValueError):
                qos.parse_class(junk)

    def test_rank_order(self):
        assert qos.rank("interactive") < qos.rank("standard") < qos.rank("batch")
        # unknown ranks standard: entries from builds predating a class
        assert qos.rank("???") == qos.rank("standard")
        assert qos.class_of_rank(0) == "interactive"
        assert qos.class_of_rank("junk") == "standard"

    def test_order_key_edf(self):
        # class dominates deadline; no deadline sorts last in class
        assert qos.order_key("interactive", None) < qos.order_key(
            "standard", 1.0
        )
        assert qos.order_key("standard", 5.0) < qos.order_key(
            "standard", 9.0
        )
        assert qos.order_key("standard", 9.0) < qos.order_key(
            "standard", None
        )

    def test_deadline_at(self):
        assert qos.deadline_at(100.0, 30) == 130.0
        assert qos.deadline_at(100.0, None) is None
        assert qos.deadline_at(100.0, 0) is None  # stop-ASAP, not EDF
        assert qos.deadline_at(100.0, "junk") is None

    def test_tenant_id(self):
        assert qos.tenant_id(None) is None
        assert qos.tenant_id("") is None  # anonymous: quota-exempt
        a, b = qos.tenant_id("tok-a"), qos.tenant_id("tok-b")
        assert a and b and a != b
        assert qos.tenant_id("tok-a") == a  # stable
        assert "tok-a" not in a  # raw credential never leaks

    def test_select_mates_prefers_leader_class(self):
        leader = _job("standard")
        mates = [_job("batch"), _job("standard"), _job("batch")]
        chosen = qos.select_mates(leader, mates, 2)
        assert [m.qos for m in chosen] == ["standard", "batch"]

    def test_select_mates_never_displaces_same_class(self):
        leader = _job("standard")
        mates = [_job("batch"), _job("batch"), _job("standard")]
        # one slot: the same-class mate wins it even though two batch
        # jobs are ahead of it in FIFO order
        chosen = qos.select_mates(leader, mates, 1)
        assert [m.qos for m in chosen] == ["standard"]

    def test_shed_fractions_default(self, monkeypatch):
        assert qos.shed_fraction("interactive") == 1.0
        assert qos.shed_fraction("standard") == 1.0  # pre-QoS parity
        assert qos.shed_fraction("batch") == 0.5
        monkeypatch.setenv("VRPMS_QOS_SHED_STANDARD", "0.75")
        assert qos.shed_fraction("standard") == 0.75


# ---------------------------------------------------------------------------
# Local queue
# ---------------------------------------------------------------------------


class TestLocalQueueQos:
    def test_pop_priority_order_property(self):
        rng = np.random.default_rng(7)
        q = JobQueue(limit=256, policy=qos.QosPolicy())
        jobs = []
        for i in range(60):
            cls = qos.CLASSES[int(rng.integers(0, 3))]
            deadline = (
                None if rng.random() < 0.3 else float(rng.uniform(0, 100))
            )
            j = _job(cls, deadline)
            jobs.append(j)
            q.push(j)
        popped = [q.pop(timeout=0) for _ in range(len(jobs))]
        # reference: stable sort of the submit order by (rank, EDF)
        expect = sorted(
            range(len(jobs)),
            key=lambda i: (qos.job_order_key(jobs[i]), i),
        )
        assert [id(p) for p in popped] == [id(jobs[i]) for i in expect]

    def test_pop_fifo_on_equal_keys(self):
        q = JobQueue(limit=8, policy=qos.QosPolicy())
        jobs = [_job() for _ in range(5)]
        for j in jobs:
            q.push(j)
        assert [q.pop(timeout=0) for _ in range(5)] == jobs

    def test_no_policy_is_fifo_regardless_of_fields(self):
        q = JobQueue(limit=8)  # VRPMS_QOS=off: no policy attached
        jobs = [
            _job("batch"), _job("interactive", 1.0), _job("standard"),
        ]
        for j in jobs:
            q.push(j)
        assert [q.pop(timeout=0) for _ in range(3)] == jobs

    def test_admit_sheds_batch_first(self):
        q = JobQueue(limit=4, policy=qos.QosPolicy())
        q.push(_job())
        q.push(_job())
        # depth 2 = batch's bound (0.5 * 4): batch sheds...
        from vrpms_tpu.sched.queue import QueueFull

        with pytest.raises(QueueFull):
            q.push(_job("batch"))
        # ...while standard and interactive still admit to the bound
        q.push(_job("interactive"))
        q.push(_job())
        with pytest.raises(QueueFull):
            q.push(_job("interactive"))  # hard bound: everyone sheds

    def test_preadmitted_jobs_skip_class_shed(self):
        # a store-claimed entry re-entering the local queue already
        # passed the SHARED admission bound: the class-fraction shed
        # must not bounce it back to the store (claim/nack livelock) —
        # only the hard bound applies (the replica's nack flow control)
        from vrpms_tpu.sched.queue import QueueFull

        q = JobQueue(limit=4, policy=qos.QosPolicy())
        q.push(_job())
        q.push(_job())
        claimed = _job("batch")
        claimed.preadmitted = True
        q.push(claimed)  # depth 2 >= batch's bound, but preadmitted
        q.push(_job("interactive"))
        with pytest.raises(QueueFull):
            over = _job("batch")
            over.preadmitted = True
            q.push(over)  # the hard bound still sheds

    def test_retry_after_uses_class_drain_rate(self):
        from vrpms_tpu.sched.queue import QueueFull

        policy = qos.QosPolicy()
        for _ in range(40):  # converge the EWMAs
            policy.note_done("batch", 10.0)
            policy.note_done("interactive", 10.0)
        q = JobQueue(limit=4, policy=policy)
        q.push(_job())
        q.push(_job())
        with pytest.raises(QueueFull) as shed:
            q.push(_job("batch"))
        # 3 jobs at-or-above batch priority (2 queued + itself floor 1
        # -> ahead counts the 2 queued) x ~10s/job, clamped <= 60
        assert shed.value.retry_after_s > 10.0
        # the batch shed's hint reflects BATCH's drain, not the global
        # EWMA default of ~1s/job
        assert shed.value.retry_after_s == policy.retry_after("batch", 2)

    def test_gather_free_rider_fill(self):
        policy = qos.QosPolicy()
        q = JobQueue(limit=16, policy=policy)
        lead = _job("standard", bucket="b")
        free_rider = _job("batch", bucket="b")
        member = _job("standard", bucket="b")
        other_bucket = _job("batch", bucket="c")
        for j in (free_rider, member, other_bucket):
            q.push(j)
        batch = gather_batch(q, lead, window_s=0.0, max_batch=3)
        # 2 open slots: the same-class member takes one, the batch
        # free rider rides the other; the other bucket stays queued
        assert batch[0] is lead
        assert batch[1] is member and batch[2] is free_rider
        assert q.pop(timeout=0) is other_bucket

    def test_gather_same_class_never_displaced(self):
        policy = qos.QosPolicy()
        q = JobQueue(limit=16, policy=policy)
        lead = _job("standard", bucket="b")
        riders = [_job("batch", bucket="b") for _ in range(2)]
        members = [_job("standard", bucket="b") for _ in range(2)]
        for j in riders + members:  # riders arrive FIRST
            q.push(j)
        batch = gather_batch(q, lead, window_s=0.0, max_batch=3)
        # 2 slots, 2 same-class members: no free rider displaces them
        assert batch == [lead] + members

    def test_depth_by_class(self):
        q = JobQueue(limit=16, policy=qos.QosPolicy())
        for cls in ("interactive", "batch", "batch", "standard"):
            q.push(_job(cls))
        assert q.depth_by_class() == {
            "interactive": 1, "standard": 1, "batch": 2,
        }
        assert JobQueue(limit=4).depth_by_class() == {}

    def test_scheduler_builds_policy_only_when_enabled(self, monkeypatch):
        jobs_mod.shutdown_scheduler()
        monkeypatch.setenv("VRPMS_QOS", "off")
        assert jobs_mod.get_scheduler()._queue_policy is None
        jobs_mod.shutdown_scheduler()
        monkeypatch.delenv("VRPMS_QOS")
        assert isinstance(
            jobs_mod.get_scheduler()._queue_policy, qos.QosPolicy
        )
        jobs_mod.shutdown_scheduler()


# ---------------------------------------------------------------------------
# Store-backed claims
# ---------------------------------------------------------------------------


def _entry(i, cls=None, deadline=None, bucket="t", tenant=None, slot=0):
    e = {"id": f"e{i}", "slot": slot, "bucket": bucket}
    if cls is not None:
        e["qos"] = cls
    if deadline is not None:
        e["deadline_at"] = deadline
    if tenant is not None:
        e["tenant"] = tenant
    return e


class TestStoreClaimQos:
    def _queue(self):
        from store.memory import InMemoryJobQueue

        return InMemoryJobQueue()

    def test_claim_order_property(self):
        rng = np.random.default_rng(3)
        q = self._queue()
        entries = []
        for i in range(40):
            cls = qos.CLASSES[int(rng.integers(0, 3))]
            deadline = (
                None if rng.random() < 0.3 else float(rng.uniform(0, 100))
            )
            e = _entry(i, cls, deadline, bucket=None)
            entries.append(e)
            q.enqueue(e)
        got = [q.claim("me", 30.0)["id"] for _ in range(len(entries))]
        expect = [
            entries[i]["id"]
            for i in sorted(
                range(len(entries)),
                key=lambda i: (qos.entry_order_key(entries[i]), i),
            )
        ]
        assert got == expect

    def test_claim_fifo_without_fields(self):
        q = self._queue()
        for i in range(5):
            q.enqueue(_entry(i))
        got = [q.claim("me", 30.0)["id"] for _ in range(5)]
        assert got == [f"e{i}" for i in range(5)]

    def test_claim_batch_leader_is_highest_priority(self):
        q = self._queue()
        q.enqueue(_entry(0, "batch"))
        q.enqueue(_entry(1, "interactive"))
        got = q.claim_batch("me", 30.0, 4)
        assert [e["id"] for e in got] == ["e1", "e0"]

    def test_claim_batch_free_rider_fill(self):
        q = self._queue()
        q.enqueue(_entry(0, "standard"))       # leader
        q.enqueue(_entry(1, "batch"))          # free rider (FIFO-first)
        q.enqueue(_entry(2, "standard"))       # same-class mate
        q.enqueue(_entry(3, "standard", bucket="other"))
        # k=2: one mate slot — the same-class mate wins it
        got = q.claim_batch("me", 30.0, 2)
        assert [e["id"] for e in got] == ["e0", "e2"]
        # next rounds: the other-bucket standard job outranks the
        # leftover batch rider, which then leads alone
        got = q.claim_batch("me", 30.0, 2)
        assert [e["id"] for e in got] == ["e3"]
        got = q.claim_batch("me", 30.0, 2)
        assert [e["id"] for e in got] == ["e1"]

    def test_claim_batch_edf_within_class(self):
        q = self._queue()
        q.enqueue(_entry(0, "standard", deadline=50.0))
        q.enqueue(_entry(1, "standard", deadline=10.0))
        q.enqueue(_entry(2, "standard"))
        got = q.claim_batch("me", 30.0, 3)
        assert [e["id"] for e in got] == ["e1", "e0", "e2"]

    def test_depth_maps(self):
        q = self._queue()
        q.enqueue(_entry(0, "interactive", tenant="tA"))
        q.enqueue(_entry(1, "batch", tenant="tA"))
        q.enqueue(_entry(2, tenant="tB"))
        q.enqueue(_entry(3))
        assert q.depth_by_class() == {
            "interactive": 1, "standard": 2, "batch": 1,
        }
        assert q.tenant_depths() == {"tA": 2, "tB": 1}

    def test_tenant_accounting_is_fleet_wide(self):
        # entries stay counted while LEASED (another replica is
        # running them) — the property per-tenant quotas divide by
        q = self._queue()
        q.enqueue(_entry(0, tenant="tA"))
        q.enqueue(_entry(1, tenant="tA"))
        claimed = q.claim("replica-1", 30.0)
        assert claimed["tenant"] == "tA"
        assert q.tenant_depths() == {"tA": 2}  # 1 leased + 1 queued
        assert q.ack("replica-1", claimed["id"])
        assert q.tenant_depths() == {"tA": 1}


# ---------------------------------------------------------------------------
# Stale-deadline fast-fail
# ---------------------------------------------------------------------------


class TestStaleDeadlineFastFail:
    def test_spent_budget_dies_before_prepare(self):
        # a claimed entry whose whole timeLimit was spent in queue
        # wait: materialize fails it clean WITHOUT parsing/preparing
        # (the payload here would not even parse — proof the parse
        # never ran)
        entry = {
            "id": "stale-1",
            "slot": 0,
            "bucket": "t",
            "qos": "standard",
            "time_limit": 2.0,
            "submitted_at": time.time() - 10.0,
            "payload": {"content": {"not": "parseable"}},
        }
        before = _shed_count("deadline_exhausted", "standard")
        job = jobs_mod._materialize_entry(entry, "r-test")
        assert job.status == "failed"
        assert job.errors[0]["what"] == "Deadline exceeded"
        assert "deadline exhausted" in job.errors[0]["reason"]
        assert _shed_count("deadline_exhausted", "standard") == before + 1

    def test_fresh_budget_is_not_fast_failed(self):
        entry = {
            "id": "fresh-1",
            "slot": 0,
            "bucket": "t",
            "qos": "standard",
            "time_limit": 300.0,
            "submitted_at": time.time(),
            "payload": {"content": {}},
        }
        job = jobs_mod._materialize_entry(entry, "r-test")
        # it fails — the payload is unparseable — but through the
        # parse path, not the deadline fast-fail
        assert job.status == "failed"
        assert job.errors[0]["what"] != "Deadline exceeded"

    def test_off_switch_skips_fast_fail(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QOS", "off")
        entry = {
            "id": "stale-2",
            "slot": 0,
            "bucket": "t",
            "time_limit": 2.0,
            "submitted_at": time.time() - 10.0,
            "payload": {"content": {}},
        }
        job = jobs_mod._materialize_entry(entry, "r-test")
        assert job.errors[0]["what"] != "Deadline exceeded"


def _shed_count(reason, cls) -> float:
    """Read vrpms_jobs_shed_total{reason,qos} back out of the rendered
    exposition (the public surface, so the test also guards the label
    names)."""
    text = service_obs.REGISTRY.render()
    for line in text.splitlines():
        if (
            line.startswith("vrpms_jobs_shed_total{")
            and f'reason="{reason}"' in line
            and f'qos="{cls}"' in line
        ):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def server():
    import os

    os.environ["VRPMS_STORE"] = "memory"
    from service.app import serve

    jobs_mod.shutdown_scheduler()
    srv = serve(port=0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    jobs_mod.shutdown_scheduler()


def _post(base, path, body):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=300) as resp:
            return resp.status, json.loads(resp.read()), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), e.headers


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=60) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _seed_dataset(key="qos7", n=7, seed=11):
    rng = np.random.default_rng(seed)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        key, [{"id": i, "demand": 2 if i else 0} for i in range(n)]
    )
    mem.seed_durations(key, d.tolist())


def _body(key="qos7", n=7, **over):
    body = {
        "problem": "vrp",
        "algorithm": "sa",
        "solutionName": f"qos-{key}",
        "solutionDescription": "t",
        "locationsKey": key,
        "durationsKey": key,
        "capacities": [2 * n] * 3,
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": 1,
        "iterationCount": 200,
        "populationSize": 8,
    }
    body.update(over)
    return body


def _poll(base, job_id, timeout=120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status, resp = _get(base, f"/api/jobs/{job_id}")
        assert status == 200, resp
        if resp["job"]["status"] in ("done", "failed"):
            return resp["job"]
        time.sleep(0.05)
    raise AssertionError(f"job {job_id} never finished")


def _blocker_body(**over):
    """A job that occupies the worker for ~its timeLimit."""
    return _body(
        iterationCount=500_000, populationSize=64, timeLimit=3, **over
    )


class TestQosHTTP:
    @pytest.fixture(autouse=True)
    def env(self, server, monkeypatch):
        jobs_mod.shutdown_scheduler()
        monkeypatch.setenv("VRPMS_SCHED_QUEUE", "4")
        _seed_dataset()
        yield
        jobs_mod.shutdown_scheduler()

    def test_selective_shed_batch_first_with_per_class_retry(self, server):
        # seed distinct per-class drain EWMAs so the Retry-After
        # hints are visibly per class
        policy = jobs_mod.get_qos_policy()
        for _ in range(40):
            policy.note_done("batch", 30.0)
            policy.note_done("interactive", 1.0)
        # occupy the worker, then fill the queue to batch's bound
        # (0.5 x 4 = 2)
        status, resp, _ = _post(server, "/api/jobs",
                                _blocker_body(seed=50))
        assert status == 202, resp
        blocker = resp["jobId"]
        time.sleep(0.3)  # blocker picked up; queue empty again
        for i in (1, 2):
            status, resp, _ = _post(
                server, "/api/jobs", _body(seed=50 + i)
            )
            assert status == 202, resp
        # batch sheds at depth 2...
        status, resp, batch_headers = _post(
            server, "/api/jobs", _body(seed=60, qos="batch")
        )
        assert status == 429, resp
        assert resp["errors"][0]["what"] == "Too busy"
        batch_retry = int(batch_headers["Retry-After"])
        assert batch_retry >= 20  # ~2 jobs ahead x ~30s batch drain
        # ...while interactive still admits past it...
        status, resp, _ = _post(
            server, "/api/jobs", _body(seed=61, qos="interactive")
        )
        assert status == 202, resp
        # ...until the hard bound, where ITS Retry-After reflects the
        # interactive drain rate, not batch's
        status, resp, _ = _post(server, "/api/jobs", _body(seed=62))
        assert status == 202, resp
        status, resp, headers = _post(
            server, "/api/jobs", _body(seed=63, qos="interactive")
        )
        assert status == 429, resp
        assert int(headers["Retry-After"]) < batch_retry
        _poll(server, blocker, timeout=60)

    def test_interactive_pops_before_earlier_batch(self, server):
        # with the worker busy, a later interactive submit must start
        # before an earlier batch submit (priority pop)
        status, resp, _ = _post(server, "/api/jobs",
                                _blocker_body(seed=70))
        assert status == 202, resp
        blocker = resp["jobId"]
        time.sleep(0.3)
        status, resp, _ = _post(
            server, "/api/jobs",
            _body(seed=71, qos="batch", iterationCount=100,
                  populationSize=4),
        )
        assert status == 202, resp
        batch_id = resp["jobId"]
        status, resp, _ = _post(
            server, "/api/jobs",
            _body(seed=72, qos="interactive", iterationCount=120,
                  populationSize=4),
        )
        assert status == 202, resp
        inter_id = resp["jobId"]
        inter = _poll(server, inter_id, timeout=60)
        batch = _poll(server, batch_id, timeout=60)
        assert inter["status"] == "done" and batch["status"] == "done"
        # different iteration counts = different buckets: no merge, so
        # start order is pop order
        assert inter["startedAt"] < batch["startedAt"], (inter, batch)
        _poll(server, blocker, timeout=60)

    def test_tenant_quota_sheds_only_that_tenant(self, server, monkeypatch):
        monkeypatch.setenv("VRPMS_QOS_TENANT_QUOTA", "1")
        mem.register_token("tok-a", "a@example.com")
        mem.register_token("tok-b", "b@example.com")
        status, resp, _ = _post(
            server, "/api/jobs", _blocker_body(seed=80, auth="tok-a")
        )
        assert status == 202, resp
        blocker = resp["jobId"]
        time.sleep(0.3)
        # tenant A is at quota while its job runs
        status, resp, headers = _post(
            server, "/api/jobs", _body(seed=81, auth="tok-a")
        )
        assert status == 429, resp
        assert "tenant" in resp["errors"][0]["reason"]
        assert int(headers["Retry-After"]) >= 1
        # other tenants and anonymous callers are unaffected
        status, resp, _ = _post(
            server, "/api/jobs", _body(seed=82, auth="tok-b")
        )
        assert status == 202, resp
        status, resp, _ = _post(server, "/api/jobs", _body(seed=83))
        assert status == 202, resp
        # the quota slot frees at the terminal transition
        _poll(server, blocker, timeout=60)
        status, resp, _ = _post(
            server, "/api/jobs", _body(seed=84, auth="tok-a")
        )
        assert status == 202, resp
        _poll(server, resp["jobId"], timeout=60)

    def test_sync_endpoint_quota_shed(self, server, monkeypatch):
        monkeypatch.setenv("VRPMS_QOS_TENANT_QUOTA", "1")
        mem.register_token("tok-c", "c@example.com")
        status, resp, _ = _post(
            server, "/api/jobs", _blocker_body(seed=90, auth="tok-c")
        )
        assert status == 202, resp
        blocker = resp["jobId"]
        time.sleep(0.3)
        body = _body(seed=91, auth="tok-c")
        del body["problem"], body["algorithm"]
        status, resp, headers = _post(server, "/api/vrp/sa", body)
        assert status == 429, resp
        assert "tenant" in resp["errors"][0]["reason"]
        assert "Retry-After" in headers
        _poll(server, blocker, timeout=60)

    def test_ready_reports_class_depths_and_tenants(self, server,
                                                    monkeypatch):
        monkeypatch.setenv("VRPMS_QOS_TENANT_QUOTA", "4")
        mem.register_token("tok-d", "d@example.com")
        status, resp, _ = _post(server, "/api/jobs",
                                _blocker_body(seed=95, auth="tok-d"))
        assert status == 202, resp
        blocker = resp["jobId"]
        time.sleep(0.3)
        status, resp, _ = _post(
            server, "/api/jobs", _body(seed=96, qos="batch", auth="tok-d")
        )
        assert status == 202, resp
        status, ready = _get(server, "/api/ready")
        assert status == 200, ready
        qinfo = ready["qos"]
        assert set(qinfo["queued"]) == set(qos.CLASSES)
        assert qinfo["queued"]["batch"] >= 1
        assert qinfo["tenantQuota"] == 4
        tenant = qos.tenant_id("tok-d")
        assert qinfo["tenants"].get(tenant, 0) >= 1
        _poll(server, blocker, timeout=60)

    def test_junk_qos_is_400_when_enabled(self, server):
        status, resp, _ = _post(
            server, "/api/jobs", _body(seed=97, qos="gold-tier")
        )
        assert status == 400, resp
        assert any(
            "qos" in e["reason"] for e in resp["errors"]
        ), resp


class TestQosDistHTTP:
    """Per-tenant quota across two in-process replicas on the shared
    store queue: the accounting is store-backed, so tenant A's job
    RUNNING ON THE PEER still counts against A at this replica's
    admission."""

    @pytest.fixture(autouse=True)
    def dist_env(self, server, monkeypatch):
        jobs_mod.shutdown_scheduler()
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_LEASE_S", "5")
        monkeypatch.setenv("VRPMS_QUEUE_POLL_MS", "10")
        monkeypatch.setenv("VRPMS_RECLAIM_S", "0.1")
        monkeypatch.setenv("VRPMS_DEPTH_MEMO_MS", "0")  # read-through
        monkeypatch.setenv("VRPMS_QOS_TENANT_QUOTA", "1")
        _seed_dataset()
        mem.register_token("tok-x", "x@example.com")
        mem.register_token("tok-y", "y@example.com")
        yield
        jobs_mod.shutdown_scheduler()

    def _peer(self):
        sched = Scheduler(
            jobs_mod._runner,
            queue_limit=64,
            window_s=0.005,
            max_batch=8,
            on_event=jobs_mod._on_event,
            watchdog_s=0,
            queue_policy=jobs_mod.get_qos_policy(),
        )
        from vrpms_tpu.sched import Replica

        rep = Replica(
            store.get_queue_store(),
            "qos-peer",
            materialize=lambda e: jobs_mod._materialize_entry(
                e, "qos-peer"
            ),
            submit=lambda job: sched.submit(
                job, backend=job.payload.get("backend") or "default"
            ),
            complete=jobs_mod._dist_complete,
            dead=jobs_mod._dist_dead,
            lease_s=5.0, poll_s=0.01, heartbeat_s=0.1, reclaim_s=0.1,
            vnodes=16,
        )
        rep._test_scheduler = sched
        return rep

    def test_quota_counts_peer_replica_work(self, server):
        peer = self._peer().start()
        try:
            status, resp, _ = _post(
                server, "/api/jobs",
                _blocker_body(seed=30, auth="tok-x"),
            )
            assert status == 202, resp
            blocker = resp["jobId"]
            # wait until SOME replica leased it (still active in the
            # store either way — queued or leased both count)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if mem._tables["job_queue"]:
                    break
                time.sleep(0.01)
            status, resp, _ = _post(
                server, "/api/jobs", _body(seed=31, auth="tok-x")
            )
            assert status == 429, resp
            assert "tenant" in resp["errors"][0]["reason"]
            # tenant Y rides through the same admission untouched
            status, resp, _ = _post(
                server, "/api/jobs", _body(seed=32, auth="tok-y")
            )
            assert status == 202, resp
            assert _poll(server, resp["jobId"])["status"] == "done"
            assert _poll(server, blocker)["status"] == "done"
            # quota frees once the entry is acked out of the store
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if not mem._tables["job_queue"]:
                    break
                time.sleep(0.01)
            status, resp, _ = _post(
                server, "/api/jobs", _body(seed=33, auth="tok-x")
            )
            assert status == 202, resp
            assert _poll(server, resp["jobId"])["status"] == "done"
        finally:
            peer.stop(drain_s=5.0)
            peer._test_scheduler.shutdown(timeout=2.0)

    def test_store_entries_carry_ordering_fields(self, server,
                                                 monkeypatch):
        # pause claiming so the entry is inspectable in the store
        monkeypatch.setenv("VRPMS_QUEUE_POLL_MS", "60000")
        jobs_mod.shutdown_scheduler()
        status, resp, _ = _post(
            server, "/api/jobs",
            _body(seed=40, qos="interactive", timeLimit=120,
                  auth="tok-x"),
        )
        assert status == 202, resp
        rows = [
            r for r in mem._tables["job_queue"].values()
            if r["id"] == resp["jobId"]
        ]
        if rows:  # not yet claimed (poll is paused after the rebuild)
            row = rows[0]
            assert row["qos"] == "interactive"
            assert row["deadline_at"] is not None
            assert row["tenant"] == qos.tenant_id("tok-x")
        # un-pause: a fresh replica (built by the next submit) claims
        # and drains both jobs
        monkeypatch.setenv("VRPMS_QUEUE_POLL_MS", "10")
        jobs_mod.shutdown_scheduler()
        status, kick, _ = _post(server, "/api/jobs", _body(seed=41))
        assert status == 202, kick
        assert _poll(server, kick["jobId"])["status"] == "done"
        assert _poll(server, resp["jobId"])["status"] == "done"


class TestQosOffGuard:
    """VRPMS_QOS=off must restore the pre-QoS contract byte for byte:
    no policy, no validation of 'qos', no entry fields, identical
    fixed-seed responses."""

    @pytest.fixture(autouse=True)
    def off_env(self, server, monkeypatch):
        jobs_mod.shutdown_scheduler()
        monkeypatch.setenv("VRPMS_QOS", "off")
        # cache off: the second identical request must SOLVE again or
        # cacheHit would (legitimately) differ between the responses
        monkeypatch.setenv("VRPMS_CACHE", "off")
        _seed_dataset()
        yield
        jobs_mod.shutdown_scheduler()

    def test_junk_qos_ignored(self, server):
        status, resp, _ = _post(
            server, "/api/jobs", _body(seed=1, qos="gold-tier")
        )
        assert status == 202, resp
        assert _poll(server, resp["jobId"])["status"] == "done"

    def test_responses_byte_identical_with_and_without_qos(self, server):
        body = _body(seed=7)
        del body["problem"], body["algorithm"]
        status, plain, _ = _post(server, "/api/vrp/sa", body)
        assert status == 200, plain
        status, with_qos, _ = _post(
            server, "/api/vrp/sa", dict(body, qos="interactive")
        )
        assert status == 200, with_qos
        status, with_junk, _ = _post(
            server, "/api/vrp/sa", dict(body, qos=12345)
        )
        assert status == 200, with_junk
        assert plain["message"] == with_qos["message"]
        assert plain["message"] == with_junk["message"]

    def test_tenant_quota_not_enforced_when_off(self, server,
                                                monkeypatch):
        monkeypatch.setenv("VRPMS_QOS_TENANT_QUOTA", "1")
        mem.register_token("tok-off", "off@example.com")
        status, resp, _ = _post(
            server, "/api/jobs", _blocker_body(seed=8, auth="tok-off")
        )
        assert status == 202, resp
        blocker = resp["jobId"]
        time.sleep(0.3)
        status, resp, _ = _post(
            server, "/api/jobs", _body(seed=9, auth="tok-off")
        )
        assert status == 202, resp  # off: quotas build nothing
        _poll(server, blocker, timeout=60)
        _poll(server, resp["jobId"], timeout=60)

    def test_ready_has_no_qos_block(self, server):
        # rebuild the scheduler first (the fixture drained it, which
        # readiness honestly reports as down)
        status, resp, _ = _post(server, "/api/jobs", _body(seed=10))
        assert status == 202, resp
        _poll(server, resp["jobId"])
        status, ready = _get(server, "/api/ready")
        assert status == 200
        assert "qos" not in ready
