"""vrpms-lint (vrpms_tpu.analysis) — the static-analysis gate's own tests.

Three layers:

  * fixture snippets per rule family — each checker catches a seeded
    violation, stays quiet on the clean twin, and honors an inline
    suppression (the catalogue test the acceptance criteria name);
  * the repo-wide run — zero unsuppressed findings, plus the
    suppression-count regression guard (a new suppression is a
    reviewed, deliberate act: bump the pin WITH the reason);
  * the config registry's runtime accessor contract, and targeted
    concurrency tests for the unsynchronized accesses the
    lock-discipline sweep found and fixed (memory-store reads,
    Scheduler.depth).
"""

from __future__ import annotations

import textwrap
import threading

import pytest

from vrpms_tpu import analysis, config
from vrpms_tpu.analysis.base import run_rules
from vrpms_tpu.analysis.config_rules import (
    DocSyncRule,
    EnvReadRule,
    UnknownVarRule,
)
from vrpms_tpu.analysis.contracts import (
    DeadSpanRule,
    EnvelopeRule,
    MetricContractRule,
    SpanNameRule,
)
from vrpms_tpu.analysis.deadcode import DeadImportRule, DeadPrivateSymbolRule
from vrpms_tpu.analysis.locks import LockDisciplineRule
from vrpms_tpu.analysis.tracing import TraceHygieneRule


def lint(tmp_path, source, rules, filename="mod.py", reference=None):
    """Write one fixture module (+ optional reference-only module) and
    run `rules` over it."""
    path = tmp_path / filename
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    refs = []
    if reference is not None:
        ref = tmp_path / "refmod.py"
        ref.write_text(textwrap.dedent(reference))
        refs = [ref]
    return run_rules(rules, [path], tmp_path, reference_paths=refs)


def rules_of(report):
    return [f.rule for f in report.findings]


# ---------------------------------------------------------------------------
# Lock discipline
# ---------------------------------------------------------------------------


class TestLockDiscipline:
    def test_instance_attr_violation_and_clean(self, tmp_path):
        report = lint(tmp_path, """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []  # guarded-by: _lock

                def good(self):
                    with self._lock:
                        return len(self._items)

                def bad(self):
                    return self._items.pop()
            """, [LockDisciplineRule()])
        assert rules_of(report) == ["lock-discipline"]
        assert report.findings[0].message.startswith("access to self._items")

    def test_module_global_violation(self, tmp_path):
        report = lint(tmp_path, """
            import threading

            _lock = threading.Lock()
            _table = {}  # guarded-by: _lock

            def good():
                with _lock:
                    _table["k"] = 1

            def bad():
                return _table.get("k")
            """, [LockDisciplineRule()])
        assert rules_of(report) == ["lock-discipline"]

    def test_condition_alias_counts_as_lock(self, tmp_path):
        report = lint(tmp_path, """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._new = threading.Condition(self._lock)
                    self._latest = None  # guarded-by: _lock

                def publish(self, snap):
                    with self._new:
                        self._latest = snap
                        self._new.notify_all()
            """, [LockDisciplineRule()])
        assert report.findings == []

    def test_locked_suffix_helper_is_trusted(self, tmp_path):
        report = lint(tmp_path, """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._state = "closed"  # guarded-by: _lock

                def _tick_locked(self):
                    self._state = "open"

                def tick(self):
                    with self._lock:
                        self._tick_locked()
            """, [LockDisciplineRule()])
        assert report.findings == []

    def test_nested_function_does_not_inherit_lock(self, tmp_path):
        report = lint(tmp_path, """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []  # guarded-by: _lock

                def leak(self):
                    with self._lock:
                        def later(self=self):
                            return self._items
                        return later
            """, [LockDisciplineRule()])
        # the closure body is skipped (conservative), but crucially the
        # with-block's lock must NOT extend into it producing a silent
        # pass for direct accesses after this pattern
        assert report.findings == []

    def test_suppression_with_reason(self, tmp_path):
        report = lint(tmp_path, """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []  # guarded-by: _lock

                def fast(self):
                    return self._items  # vrpms-lint: disable=lock-discipline (benign racy read, bounded staleness)
            """, [LockDisciplineRule()])
        assert report.findings == []
        assert len(report.suppressed) == 1

    def test_standalone_suppression_skips_blank_lines(self, tmp_path):
        report = lint(tmp_path, """
            import threading

            _lock = threading.Lock()
            _table = {}  # guarded-by: _lock

            def fast():
                # vrpms-lint: disable=lock-discipline (snapshot read; bounded staleness)

                return _table
            """, [LockDisciplineRule()])
        assert report.findings == []
        assert len(report.suppressed) == 1

    def test_nested_class_annotations_stay_scoped(self, tmp_path):
        report = lint(tmp_path, """
            import threading

            class Outer:
                class Inner:
                    def __init__(self):
                        self._ilock = threading.Lock()
                        self._data = {}  # guarded-by: _ilock

                    def bad_inner(self):
                        return self._data

                def touch(self):
                    # Outer._data is unrelated to Inner's annotation
                    return self._data
            """, [LockDisciplineRule()])
        # exactly ONE finding: Inner's own unlocked read — Outer.touch
        # must not inherit Inner's guard
        assert rules_of(report) == ["lock-discipline"]
        assert report.findings[0].message.startswith("access to self._data")
        assert report.findings[0].line == 11  # Inner.bad_inner's return

    def test_suppression_without_reason_is_a_finding(self, tmp_path):
        report = lint(tmp_path, """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []  # guarded-by: _lock

                def fast(self):
                    return self._items  # vrpms-lint: disable=lock-discipline
            """, [LockDisciplineRule()])
        assert sorted(rules_of(report)) == [
            "lock-discipline", "suppression-no-reason",
        ]


# ---------------------------------------------------------------------------
# JAX tracing hygiene
# ---------------------------------------------------------------------------


class TestTracingHygiene:
    def test_host_coercion_in_jitted_function(self, tmp_path):
        report = lint(tmp_path, """
            import jax
            import numpy as np

            @jax.jit
            def kernel(x):
                y = float(x)
                z = np.asarray(x)
                return x.sum().item()
            """, [TraceHygieneRule()])
        assert rules_of(report).count("trace-host-coercion") == 3

    def test_clean_jitted_function(self, tmp_path):
        report = lint(tmp_path, """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def kernel(x):
                n = int(x.shape[0])
                return jnp.sum(x) / n
            """, [TraceHygieneRule()])
        assert report.findings == []

    def test_python_random_in_scan_body(self, tmp_path):
        report = lint(tmp_path, """
            import random
            from jax import lax

            def body(carry, x):
                r = random.random()
                return carry + r, x

            def driver(xs):
                return lax.scan(body, 0.0, xs)
            """, [TraceHygieneRule()])
        assert "trace-python-random" in rules_of(report)

    def test_branch_on_scan_body_param(self, tmp_path):
        report = lint(tmp_path, """
            from jax import lax

            def body(carry, x):
                if x > 0:
                    carry = carry + x
                return carry, x

            def driver(xs):
                return lax.scan(body, 0.0, xs)
            """, [TraceHygieneRule()])
        assert "trace-traced-branch" in rules_of(report)

    def test_transitive_callee_is_traced(self, tmp_path):
        report = lint(tmp_path, """
            import jax

            def helper(v):
                return v.item()

            @jax.jit
            def kernel(x):
                return helper(x)
            """, [TraceHygieneRule()])
        assert "trace-host-coercion" in rules_of(report)

    def test_jit_in_loop(self, tmp_path):
        report = lint(tmp_path, """
            import jax

            def f(x):
                return x

            def run(xs):
                out = []
                for x in xs:
                    out.append(jax.jit(f)(x))
                return out
            """, [TraceHygieneRule()])
        assert "trace-jit-in-loop" in rules_of(report)

    def test_lru_cached_factory_may_jit_in_loop(self, tmp_path):
        report = lint(tmp_path, """
            import functools
            import jax

            def f(x):
                return x

            @functools.lru_cache
            def factory(n):
                for _ in range(n):
                    g = jax.jit(f)
                return g
            """, [TraceHygieneRule()])
        assert report.findings == []

    def test_unhashable_static_arg(self, tmp_path):
        report = lint(tmp_path, """
            import jax

            def f(x, opts):
                return x

            g = jax.jit(f, static_argnums=(1,))

            def call(x):
                return g(x, [1, 2, 3])
            """, [TraceHygieneRule()])
        assert "trace-unhashable-static" in rules_of(report)


# ---------------------------------------------------------------------------
# Service contracts
# ---------------------------------------------------------------------------


class TestServiceContracts:
    def test_envelope_without_attach_ids(self, tmp_path):
        report = lint(tmp_path, """
            import json

            def write_bad(handler):
                handler.wfile.write(
                    json.dumps({"success": False}).encode("utf-8")
                )

            def write_good(handler):
                resp = attach_ids(handler, {"success": True})
                handler.wfile.write(json.dumps(resp).encode("utf-8"))

            def write_sse(handler):
                handler.wfile.write(b": keep-alive\\n\\n")
            """, [EnvelopeRule()], filename="service/handlers.py")
        assert rules_of(report) == ["contract-envelope"]

    def test_metric_registered_twice(self, tmp_path):
        report = lint(tmp_path, """
            A = REGISTRY.counter("vrpms_requests_total", "requests")
            B = REGISTRY.counter("vrpms_requests_total", "requests again")
            """, [MetricContractRule()])
        assert "contract-metric-once" in rules_of(report)

    def test_metric_label_mismatch(self, tmp_path):
        report = lint(tmp_path, """
            FAILS = REGISTRY.counter(
                "vrpms_store_failures_total", "failures",
                labels=("kind", "reason"),
            )

            def record():
                FAILS.labels(kind="supabase").inc()
            """, [MetricContractRule()])
        assert "contract-metric-labels" in rules_of(report)

    def test_metric_consistent_usage_clean(self, tmp_path):
        report = lint(tmp_path, """
            FAILS = REGISTRY.counter(
                "vrpms_store_failures_total", "failures",
                labels=("kind", "reason"),
            )

            def record():
                FAILS.labels(kind="supabase", reason="timeout").inc()
            """, [MetricContractRule()])
        assert report.findings == []

    def test_unregistered_span_name(self, tmp_path):
        rule = SpanNameRule(registry=frozenset({"solve"}))
        report = lint(tmp_path, """
            from vrpms_tpu.obs import spans

            def work():
                with spans.span("solve"):
                    pass
                with spans.span("mystery.step"):
                    pass
            """, [rule])
        assert rules_of(report) == ["contract-span-name"]

    def test_real_span_registry_importable(self):
        from vrpms_tpu.obs.spans import KNOWN_SPAN_NAMES

        assert "solve" in KNOWN_SPAN_NAMES
        assert "store.resilient" in KNOWN_SPAN_NAMES

    def test_dead_span_name_flagged(self, tmp_path):
        rule = DeadSpanRule(registry=frozenset({"solve", "ghost.step"}))
        report = lint(tmp_path, """
            from vrpms_tpu.obs import spans

            KNOWN_SPAN_NAMES = frozenset({"solve", "ghost.step"})

            def work():
                with spans.span("solve"):
                    pass
            """, [rule])
        assert rules_of(report) == ["contract-span-dead"]
        assert "ghost.step" in report.findings[0].message
        # the finding anchors at the registry declaration line
        assert report.findings[0].line == 4

    def test_dead_span_silent_when_registry_site_unscanned(
        self, tmp_path
    ):
        # a partial scan (one file, no KNOWN_SPAN_NAMES declaration)
        # has not seen the emission universe — it must not call the
        # whole registry dead (the CLI-on-a-tmp-tree case)
        rule = DeadSpanRule(registry=frozenset({"solve", "ghost.step"}))
        report = lint(tmp_path, """
            def work():
                return 1
            """, [rule])
        assert report.findings == []

    def test_dead_span_clean_when_all_emitted(self, tmp_path):
        rule = DeadSpanRule(registry=frozenset({"solve", "stitch"}))
        report = lint(tmp_path, """
            from vrpms_tpu.obs import spans

            def work():
                with spans.span("solve"):
                    with spans.span_at("stitch", 0.0):
                        pass
            """, [rule])
        assert report.findings == []

    def test_dead_span_suppressed_at_registry_site(self, tmp_path):
        rule = DeadSpanRule(registry=frozenset({"retired.step"}))
        report = lint(tmp_path, """
            # vrpms-lint: disable=contract-span-dead (dashboard keeps the retired name one release)
            KNOWN_SPAN_NAMES = frozenset({"retired.step"})
            """, [rule])
        assert report.findings == []
        assert [f.rule for f in report.suppressed] == ["contract-span-dead"]


# ---------------------------------------------------------------------------
# Config discipline
# ---------------------------------------------------------------------------


class TestConfigDiscipline:
    def test_direct_env_read_flagged(self, tmp_path):
        report = lint(tmp_path, """
            import os

            A = os.environ.get("VRPMS_TIERS")
            B = os.getenv("VRPMS_TIERS")
            C = os.environ["HOME"]
            os.environ["VRPMS_STORE"] = "memory"  # writes stay legal
            """, [EnvReadRule()])
        assert rules_of(report) == ["config-env-read"] * 3

    def test_config_module_itself_exempt(self, tmp_path):
        report = lint(tmp_path, """
            import os

            def get(name):
                return os.environ.get(name)
            """, [EnvReadRule()], filename="vrpms_tpu/config.py")
        assert report.findings == []

    def test_unknown_var_literal(self, tmp_path):
        rule = UnknownVarRule(registry=frozenset({"VRPMS_TIERS"}))
        report = lint(tmp_path, """
            GOOD = "VRPMS_TIERS"
            TYPO = "VRPMS_TEIRS"
            """, [rule])
        assert rules_of(report) == ["config-unknown-var"]

    def test_doc_sync_missing_var(self, tmp_path):
        (tmp_path / "README.md").write_text("docs mention VRPMS_ALPHA only")
        report = lint(tmp_path, """
            REGISTRY = {"VRPMS_ALPHA": 1, "VRPMS_BETA": 2}
            """, [DocSyncRule()], filename="vrpms_tpu/config.py")
        assert rules_of(report) == ["config-doc-sync"]
        assert "VRPMS_BETA" in report.findings[0].message


# ---------------------------------------------------------------------------
# Dead code
# ---------------------------------------------------------------------------


class TestDeadCode:
    def test_unused_import(self, tmp_path):
        report = lint(tmp_path, """
            import json
            import math

            def area(r):
                return math.pi * r * r
            """, [DeadImportRule()])
        assert rules_of(report) == ["dead-import"]
        assert "json" in report.findings[0].message

    def test_noqa_reexport_exempt(self, tmp_path):
        report = lint(tmp_path, """
            from math import pi  # noqa: F401 (re-exported)
            """, [DeadImportRule()])
        assert report.findings == []

    def test_dead_private_symbol(self, tmp_path):
        report = lint(tmp_path, """
            def _used():
                return 1

            def _dead():
                return 2

            def entry():
                return _used()
            """, [DeadPrivateSymbolRule()])
        assert rules_of(report) == ["dead-private-symbol"]
        assert "_dead" in report.findings[0].message

    def test_reference_tree_keeps_symbol_alive(self, tmp_path):
        report = lint(tmp_path, """
            def _poked_by_tests():
                return 1
            """, [DeadPrivateSymbolRule()], reference="""
            import mod

            def test_it():
                assert mod._poked_by_tests() == 1
            """)
        assert report.findings == []


# ---------------------------------------------------------------------------
# The repo itself
# ---------------------------------------------------------------------------


#: the reviewed suppression budget: every entry documents a deliberate
#: exception (fast-path reads under double-checked locking). If you add
#: a suppression, justify it in the review and bump this pin.
EXPECTED_SUPPRESSIONS = 3


class TestRepoClean:
    @pytest.fixture(scope="class")
    def repo_report(self):
        return analysis.run()

    def test_zero_unsuppressed_findings(self, repo_report):
        assert repo_report.parse_errors == []
        assert repo_report.findings == [], (
            "vrpms-lint found violations:\n"
            + "\n".join(f.render() for f in repo_report.findings)
        )

    def test_suppression_count_regression_guard(self, repo_report):
        assert len(repo_report.suppressed) == EXPECTED_SUPPRESSIONS, (
            f"suppression count changed "
            f"({len(repo_report.suppressed)} != {EXPECTED_SUPPRESSIONS}); "
            "suppressions are a reviewed budget — update "
            "EXPECTED_SUPPRESSIONS with a justification"
        )

    def test_every_suppression_is_lock_fast_path(self, repo_report):
        # today's budget is exactly the GIL-safe double-checked
        # fast-path reads; anything else deserves its own review
        assert all(
            f.rule == "lock-discipline" for f in repo_report.suppressed
        )

    def test_rule_instances_are_reusable_across_runs(self, repo_report):
        # project rules must reset collect() state per run: a reused
        # rule list (the documented programmatic entry point) must not
        # accumulate duplicate registrations into spurious findings
        rules = analysis.default_rules()
        first = analysis.run(rules=rules)
        second = analysis.run(rules=rules)
        assert first.findings == []
        assert second.findings == []

    def test_list_rules_names_match_finding_ids(self):
        # every id a finding can carry (and a suppression must name)
        # appears in --list-rules output — umbrella class names alone
        # would make disables unguessable
        import io
        from contextlib import redirect_stdout

        from vrpms_tpu.analysis.__main__ import main

        buf = io.StringIO()
        with redirect_stdout(buf):
            assert main(["--list-rules"]) == 0
        listed = buf.getvalue()
        for rule_id in (
            "lock-discipline", "trace-host-coercion", "trace-python-random",
            "trace-traced-branch", "trace-jit-in-loop",
            "trace-unhashable-static", "contract-envelope",
            "contract-metric-once", "contract-metric-labels",
            "contract-span-name", "contract-span-dead", "config-env-read",
            "config-unknown-var",
            "config-doc-sync", "dead-import", "dead-private-symbol",
        ):
            assert rule_id in listed, f"{rule_id} missing from --list-rules"

    def test_cli_gate_fails_injected_violation(self, tmp_path):
        import subprocess
        import sys

        bad = tmp_path / "injected.py"
        bad.write_text('import os\nX = os.environ.get("VRPMS_TIERS")\n')
        proc = subprocess.run(
            [sys.executable, "-m", "vrpms_tpu.analysis", str(bad),
             "--root", str(tmp_path)],
            capture_output=True, text=True,
            cwd=str(analysis.REPO_ROOT),
        )
        assert proc.returncode == 1
        assert "config-env-read" in proc.stdout

    def test_cli_clean_tree_exits_zero(self, tmp_path):
        import subprocess
        import sys

        ok = tmp_path / "clean.py"
        ok.write_text("VALUE = 1\n")
        proc = subprocess.run(
            [sys.executable, "-m", "vrpms_tpu.analysis", str(ok),
             "--root", str(tmp_path)],
            capture_output=True, text=True,
            cwd=str(analysis.REPO_ROOT),
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# Config registry runtime accessor
# ---------------------------------------------------------------------------


class TestConfigRegistry:
    def test_typed_get_and_defaults(self, monkeypatch):
        monkeypatch.delenv("VRPMS_SCHED_QUEUE", raising=False)
        assert config.get("VRPMS_SCHED_QUEUE") == 64
        monkeypatch.setenv("VRPMS_SCHED_QUEUE", "8")
        assert config.get("VRPMS_SCHED_QUEUE") == 8
        monkeypatch.setenv("VRPMS_SCHED_QUEUE", "junk")
        assert config.get("VRPMS_SCHED_QUEUE") == 64  # forgiving parse

    def test_switch_spellings(self, monkeypatch):
        for off in ("off", "0", "FALSE", " no "):
            monkeypatch.setenv("VRPMS_PROGRESS", off)
            assert config.enabled("VRPMS_PROGRESS") is False
        monkeypatch.setenv("VRPMS_PROGRESS", "on")
        assert config.enabled("VRPMS_PROGRESS") is True
        monkeypatch.delenv("VRPMS_PROGRESS", raising=False)
        assert config.enabled("VRPMS_PROGRESS") is True  # default on

    def test_unregistered_name_fails_loudly(self):
        with pytest.raises(KeyError):
            config.get("VRPMS_NOT_A_KNOB")
        with pytest.raises(KeyError):
            config.raw("VRPMS_NOT_A_KNOB")

    def test_enabled_rejects_non_switch(self):
        with pytest.raises(TypeError):
            config.enabled("VRPMS_TIERS")

    def test_markdown_table_covers_registry(self):
        table = config.markdown_table()
        for var in config.iter_vars():
            assert f"`{var.name}`" in table

    def test_raw_returns_uninterpreted(self, monkeypatch):
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        assert config.raw("VRPMS_STORE") == "faulty:down"
        monkeypatch.delenv("VRPMS_STORE", raising=False)
        assert config.raw("VRPMS_STORE") is None


# ---------------------------------------------------------------------------
# Concurrency regressions for the lock-discipline fixes
# ---------------------------------------------------------------------------


class TestLockFixConcurrency:
    """Stress the paths the sweep locked: unguarded reads of the
    memory-store tables and Scheduler's worker map were benign only by
    CPython-GIL accident; these pin the now-locked behavior under real
    thread interleaving."""

    def test_memory_store_concurrent_read_write(self):
        from store import memory

        memory.reset()
        db = memory.InMemoryDatabaseVRP(None)
        errors: list = []
        stop = threading.Event()

        def writer(i):
            n = 0
            while not stop.is_set():
                db.save_job(f"job-{i}-{n % 50}", {"status": "done", "n": n})
                n += 1

        def reader(i):
            while not stop.is_set():
                try:
                    db._fetch_job(f"job-{i}-0")
                    memory.saved_solutions()
                except Exception as e:  # pragma: no cover - the regression
                    errors.append(e)
                    return

        threads = [
            *(threading.Thread(target=writer, args=(i,)) for i in range(3)),
            *(threading.Thread(target=reader, args=(i,)) for i in range(3)),
        ]
        for t in threads:
            t.start()
        import time as _time

        _time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        memory.reset()
        assert errors == []

    def test_scheduler_depth_during_submits_and_restarts(self):
        from vrpms_tpu.sched.queue import Job, QueueFull
        from vrpms_tpu.sched.worker import Scheduler

        def runner(jobs):
            for job in jobs:
                job.result = {"ok": True}

        sched = Scheduler(runner, queue_limit=256, window_s=0.0,
                          watchdog_s=0.0)
        errors: list = []
        stop = threading.Event()
        backends = [f"b{i}" for i in range(4)]

        def submitter(backend):
            while not stop.is_set():
                try:
                    sched.submit(Job(payload={}), backend=backend)
                except QueueFull:
                    pass  # backpressure is expected under the hammer
                except Exception as e:
                    errors.append(e)
                    return

        def prober():
            while not stop.is_set():
                try:
                    for b in backends:
                        sched.depth(b)
                    sched.queues()
                    sched.worker_health()
                except Exception as e:  # pragma: no cover - the regression
                    errors.append(e)
                    return

        threads = [
            *(threading.Thread(target=submitter, args=(b,))
              for b in backends),
            threading.Thread(target=prober),
            threading.Thread(target=prober),
        ]
        for t in threads:
            t.start()
        import time as _time

        _time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        sched.shutdown()
        assert errors == []
