"""Giant-instance decomposition (cluster -> batched tier solves ->
stitch): the oracle-equivalence, stitch-validity, and batched-launch
contracts of vrpms_tpu.core.decompose + service wiring (VRPMS_DECOMP),
plus the satellites that ride with it — GA/ACO continuation schedules,
the shard-sum lower bound, and the streamed CVRPLIB parse.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from vrpms_tpu.core import decompose
from vrpms_tpu.io.synth import synth_clustered_coords

#: a deliberately tiny ladder so decomposition engages at test sizes
#: (ceiling 32 nodes) without paying giant compiles
SMALL_LADDER = "n=8,16,32;v=1,2,4,8;t=1"


def _euclid(coords):
    return np.linalg.norm(coords[:, None] - coords[None, :], axis=-1)


def _giant_request(n_nodes=61, n_vehicles=6, seed=3, slack=1.3):
    coords, demands = synth_clustered_coords(n_nodes, 4, seed=seed)
    d = _euclid(coords)
    locations = [
        {"id": i, "demand": float(demands[i])} for i in range(n_nodes)
    ]
    cap = float(np.ceil(demands.sum() * slack / n_vehicles))
    params = {
        "name": "giant",
        "capacities": [cap] * n_vehicles,
        "start_times": [0.0] * n_vehicles,
        "ignored_customers": [],
        "completed_customers": [],
    }
    opts = {"seed": 7, "iteration_count": 300, "population_size": 16}
    return locations, d, params, opts


def _run(params, opts, locations, matrix):
    from service.solve import run_vrp

    errors: list = []
    res = run_vrp("sa", params, opts, {}, locations, matrix, errors)
    assert res is not None, errors
    assert not errors, errors
    return res


# ---------------------------------------------------------------------------
# Partitioning + plan invariants
# ---------------------------------------------------------------------------


class TestPartition:
    def test_matrix_partition_covers_every_customer_once(self):
        coords, _ = synth_clustered_coords(80, 5, seed=1)
        labels, dist = decompose.partition_matrix(_euclid(coords), 4, 25)
        assert labels.shape == (79,) and dist.shape == (79, 4)
        counts = np.bincount(labels, minlength=4)
        assert counts.sum() == 79 and counts.max() <= 25

    def test_coords_partition_covers_every_customer_once(self):
        coords, _ = synth_clustered_coords(80, 5, seed=2)
        labels, dist = decompose.partition_coords(coords, 4, 25, seed=0)
        counts = np.bincount(labels, minlength=4)
        assert counts.sum() == 79 and counts.max() <= 25

    def test_partitions_are_deterministic(self):
        coords, _ = synth_clustered_coords(60, 4, seed=5)
        d = _euclid(coords)
        a = decompose.partition_matrix(d, 3, 25)[0]
        b = decompose.partition_matrix(d, 3, 25)[0]
        assert np.array_equal(a, b)

    def test_boundary_band_is_frontier_subset_and_capped(self):
        coords, _ = synth_clustered_coords(80, 5, seed=1)
        labels, dist = decompose.partition_matrix(_euclid(coords), 4, 25)
        band = decompose.boundary_band(labels, dist, ratio=1.5, cap=10)
        assert band.size <= 10
        assert band.size == np.unique(band).size
        assert band.size == 0 or (band.min() >= 1 and band.max() <= 79)


class TestPlan:
    def test_fleet_slices_disjoint_and_cover(self, monkeypatch):
        monkeypatch.setenv("VRPMS_TIERS", SMALL_LADDER)
        locations, d, params, _ = _giant_request()
        demands = [loc["demand"] for loc in locations]
        plan = decompose.build_plan(
            d, demands, [0.0] * len(locations), params["capacities"],
            params["start_times"],
        )
        all_members = np.concatenate(plan.members)
        assert np.array_equal(
            np.sort(all_members), np.arange(1, len(locations))
        )
        used = np.concatenate(
            list(plan.vehicles) + [plan.boundary_vehicles]
        )
        assert used.size == np.unique(used).size
        assert used.size <= len(params["capacities"])
        assert set(plan.boundary) <= set(all_members.tolist())
        assert plan.tier_n == 32  # shards fit one canonical tier
        assert plan.lower_bound is not None and plan.lower_bound > 0

    def test_too_few_vehicles_raises_in_core(self, monkeypatch):
        monkeypatch.setenv("VRPMS_TIERS", SMALL_LADDER)
        locations, d, params, _ = _giant_request(n_vehicles=1)
        demands = [loc["demand"] for loc in locations]
        with pytest.raises(ValueError, match="vehicles"):
            decompose.build_plan(
                d, demands, [0.0] * len(locations),
                params["capacities"], params["start_times"],
            )

    def test_unplannable_fleet_falls_back_monolithic(self, monkeypatch):
        """A default-on optimization must never turn a solvable request
        into an error: too few vehicles for the shard count keeps the
        pre-decomposition monolithic path."""
        monkeypatch.setenv("VRPMS_TIERS", SMALL_LADDER)
        locations, d, params, opts = _giant_request(n_vehicles=1)
        # one huge vehicle: monolithically solvable, never decomposable
        params["capacities"] = [1e9]
        opts = dict(opts, iteration_count=100)
        res = _run(params, opts, locations, d)
        assert "decomposition" not in res
        served = sorted(
            c for v in res["vehicles"] for c in v["tour"][1:-1]
        )
        assert served == list(range(1, len(locations)))

    def test_shard_sum_bound_floors_shard_respecting_routes(
        self, monkeypatch
    ):
        monkeypatch.setenv("VRPMS_TIERS", SMALL_LADDER)
        locations, d, params, _ = _giant_request()
        demands = [loc["demand"] for loc in locations]
        plan = decompose.build_plan(
            d, demands, [0.0] * len(locations), params["capacities"],
            params["start_times"],
        )
        # one round trip per shard (a valid shard-respecting route set)
        total = sum(
            d[0, m[0]]
            + sum(d[a, b] for a, b in zip(m[:-1], m[1:]))
            + d[m[-1], 0]
            for m in plan.members
        )
        assert plan.lower_bound <= total + 1e-6


class TestRepairPrimitives:
    def test_strip_band_preserves_relative_order(self):
        routes = [[5, 2, 9], [7, 3], []]
        order = decompose.strip_band(routes, np.asarray([2, 3, 9]))
        assert order == [2, 9, 3]
        assert routes == [[5], [7], []]

    def test_rebalance_restores_feasibility(self, monkeypatch):
        monkeypatch.setenv("VRPMS_TIERS", SMALL_LADDER)
        locations, d, params, _ = _giant_request()
        demands = np.asarray([loc["demand"] for loc in locations])
        plan = decompose.build_plan(
            d, demands, [0.0] * len(locations), params["capacities"],
            params["start_times"],
        )
        caps = plan.arrays["capacities"]
        # cram everything onto vehicle 0: grossly infeasible
        routes = [list(range(1, len(locations)))] + [
            [] for _ in range(len(caps) - 1)
        ]
        decompose.rebalance_capacity(plan, routes)
        loads = [sum(demands[c] for c in r) for r in routes]
        assert all(l <= c + 1e-6 for l, c in zip(loads, caps))
        served = sorted(c for r in routes for c in r)
        assert served == list(range(1, len(locations)))


class TestShardRollup:
    class _Sink:
        def __init__(self):
            self.calls = []
            self.cancelled = False

        def record(self, best, iters, evals_per_iter):
            # mirror ProgressSink: unreadable best counts evals only
            try:
                cost = float(np.min(np.asarray(best)))
            except Exception:
                cost = None
            self.calls.append((cost, iters))

        def note_cancel_seen(self):
            pass

    def test_rollup_publishes_monotone_sum_once_complete(self):
        sink = self._Sink()
        roll = decompose.ShardRollup(sink, 2)
        roll.begin([0])
        roll.record(np.asarray([[10.0, 12.0]]), 5, 1.0)
        # shard 1 has no incumbent yet: eval-only forward, no cost
        assert sink.calls[-1][0] is None
        roll.begin([1])
        roll.record(np.asarray([[7.0, 9.0]]), 5, 1.0)
        assert sink.calls[-1][0] == pytest.approx(17.0)
        roll.record(np.asarray([[6.0, 9.0]]), 5, 1.0)
        assert sink.calls[-1][0] == pytest.approx(16.0)
        roll.publish_total(15.5)
        assert sink.calls[-1][0] == pytest.approx(15.5)


# ---------------------------------------------------------------------------
# The decomposition oracle: off == on below the ceiling, byte-identical
# ---------------------------------------------------------------------------


class TestOracleEquivalence:
    def _small_request(self):
        rng = np.random.default_rng(0)
        n = 13
        coords = rng.uniform(0, 100, size=(n, 2))
        d = _euclid(coords)
        locations = [{"id": i, "demand": 1.0} for i in range(n)]
        params = {
            "name": "small",
            "capacities": [8.0, 8.0],
            "start_times": [0.0, 0.0],
            "ignored_customers": [],
            "completed_customers": [],
        }
        opts = {"seed": 5, "iteration_count": 200, "population_size": 8}
        return locations, d.tolist(), params, opts

    @pytest.mark.parametrize("mode", ["on", "auto"])
    def test_within_one_tier_decomp_is_a_byte_identical_noop(
        self, monkeypatch, mode
    ):
        locations, d, params, opts = self._small_request()
        monkeypatch.setenv("VRPMS_DECOMP", "off")
        off = _run(params, dict(opts), locations, d)
        monkeypatch.setenv("VRPMS_DECOMP", mode)
        on = _run(params, dict(opts), locations, d)
        assert json.dumps(off, sort_keys=True) == json.dumps(
            on, sort_keys=True
        )
        assert "decomposition" not in on


# ---------------------------------------------------------------------------
# The full service path above the ceiling
# ---------------------------------------------------------------------------


class TestDecomposedService:
    @pytest.mark.parametrize("seed", [3, 11])
    def test_giant_request_solves_valid_and_bounded(
        self, monkeypatch, seed
    ):
        monkeypatch.setenv("VRPMS_TIERS", SMALL_LADDER)
        locations, d, params, opts = _giant_request(seed=seed)
        res = _run(params, opts, locations, d)
        dec = res["decomposition"]
        assert dec["shards"] >= 2 and dec["tier"] == 32
        assert dec["launches"] == -(-dec["shards"] // dec["maxBatch"])
        # every customer served exactly once
        served = sorted(
            c for v in res["vehicles"] for c in v["tour"][1:-1]
        )
        assert served == list(range(1, len(locations)))
        # capacity respected after boundary repair + rebalance
        for v in res["vehicles"]:
            assert v["load"] <= v["capacity"] + 1e-6
            assert v["tour"][0] == 0 and v["tour"][-1] == 0
        # bounded gap vs the shard-sum lower bound
        assert dec["lowerBound"] is not None
        assert res["durationSum"] >= dec["lowerBound"] - 1e-6
        assert res["durationSum"] <= 4.0 * dec["lowerBound"]

    def test_forced_solo_dispatch_launches_per_shard(self, monkeypatch):
        monkeypatch.setenv("VRPMS_TIERS", SMALL_LADDER)
        monkeypatch.setenv("VRPMS_SCHED_MAX_BATCH", "1")
        locations, d, params, opts = _giant_request()
        res = _run(params, opts, locations, d)
        dec = res["decomposition"]
        assert dec["maxBatch"] == 1
        assert dec["launches"] == dec["shards"]

    def test_decomp_off_keeps_the_monolithic_path(self, monkeypatch):
        monkeypatch.setenv("VRPMS_TIERS", SMALL_LADDER)
        monkeypatch.setenv("VRPMS_DECOMP", "off")
        locations, d, params, opts = _giant_request()
        opts = dict(opts, iteration_count=100)
        res = _run(params, opts, locations, d)
        assert "decomposition" not in res
        served = sorted(
            c for v in res["vehicles"] for c in v["tour"][1:-1]
        )
        assert served == list(range(1, len(locations)))

    def test_unsupported_options_keep_the_monolithic_path(
        self, monkeypatch
    ):
        monkeypatch.setenv("VRPMS_TIERS", SMALL_LADDER)
        locations, d, params, opts = _giant_request()
        opts = dict(opts, iteration_count=100, local_search=True)
        res = _run(params, opts, locations, d)
        assert "decomposition" not in res

    def test_deterministic_at_fixed_seed(self, monkeypatch):
        monkeypatch.setenv("VRPMS_TIERS", SMALL_LADDER)
        locations, d, params, opts = _giant_request()
        a = _run(params, dict(opts), locations, d)
        b = _run(params, dict(opts), locations, d)
        assert json.dumps(a, sort_keys=True) == json.dumps(
            b, sort_keys=True
        )


# ---------------------------------------------------------------------------
# Streamed CVRPLIB parse (no O(n^2) matrix for giant files)
# ---------------------------------------------------------------------------


def _vrp_text(coords, demands, capacity, k=4):
    n = len(coords)
    lines = [
        f"NAME : synth-n{n}-k{k}",
        "TYPE : CVRP",
        f"DIMENSION : {n}",
        "EDGE_WEIGHT_TYPE : EUC_2D",
        f"CAPACITY : {capacity}",
        "NODE_COORD_SECTION",
    ]
    lines += [
        f"{i + 1} {coords[i][0]:.1f} {coords[i][1]:.1f}" for i in range(n)
    ]
    lines.append("DEMAND_SECTION")
    lines += [f"{i + 1} {int(demands[i])}" for i in range(n)]
    lines += ["DEPOT_SECTION", "1", "-1", "EOF"]
    return "\n".join(lines)


class TestStreamedCvrplib:
    def test_streamed_parse_skips_matrix_and_keeps_coords(self):
        from vrpms_tpu.io.cvrplib import parse_cvrplib

        coords, demands = synth_clustered_coords(30, 3, seed=4)
        text = _vrp_text(coords, demands, 50)
        inst, meta = parse_cvrplib(text, max_dense_n=10)
        assert inst is None and meta["streamed"] is True
        assert meta["coords"].shape == (30, 2)
        assert len(meta["demands"]) == 30
        assert len(meta["capacities"]) == 4  # the -k4 NAME suffix

    def test_shard_matrix_matches_dense_parse(self):
        from vrpms_tpu.io.cvrplib import parse_cvrplib, shard_matrix

        coords, demands = synth_clustered_coords(30, 3, seed=4)
        text = _vrp_text(coords, demands, 50)
        dense, _ = parse_cvrplib(text)
        _, meta = parse_cvrplib(text, max_dense_n=10)
        nodes = [0, 3, 7, 21]
        sub = shard_matrix(meta["coords"], nodes)
        full = np.asarray(dense.durations[0])[np.ix_(nodes, nodes)]
        np.testing.assert_allclose(sub, full, atol=1e-5)
        # _Dist's coords-mode accessors are the same convention: the
        # submatrix delegates to shard_matrix and the scalar leg must
        # match it entry for entry
        dist = decompose._Dist(
            {"coords": meta["coords"], "round_nint": True}
        )
        np.testing.assert_allclose(dist.sub(nodes), sub, atol=1e-5)
        assert dist.point(3, 21) == pytest.approx(float(sub[1, 3]))

    def test_streamed_giant_solves_without_dense_matrix(
        self, monkeypatch
    ):
        """The full streamed pipeline: parse (no O(n^2) matrix) ->
        coords plan -> batched shard solves -> stitch -> valid routes,
        with every submatrix built on demand from coordinates."""
        from vrpms_tpu.core.cost import CostWeights
        from vrpms_tpu.io.cvrplib import parse_cvrplib
        from vrpms_tpu.solvers import SAParams

        monkeypatch.setenv("VRPMS_TIERS", SMALL_LADDER)
        coords, demands = synth_clustered_coords(61, 4, seed=3)
        cap = float(np.ceil(demands.sum() * 1.3 / 6))
        text = _vrp_text(coords, demands, cap, k=6)
        inst, meta = parse_cvrplib(text, max_dense_n=32)
        assert inst is None and meta["streamed"] is True
        plan = decompose.build_plan(
            None,
            meta["demands"],
            [0.0] * 61,
            meta["capacities"],
            meta["start_times"],
            coords=meta["coords"],
            round_nint=meta["round_nint"],
        )
        assert "durations" not in plan.arrays  # nothing O(n^2) exists
        assert plan.lower_bound is not None and plan.lower_bound > 0
        w = CostWeights.make()
        insts = decompose.shard_instances(plan)
        results, launches = decompose.solve_shards(
            insts, list(range(len(insts))),
            SAParams(n_chains=8, n_iters=100), weights=w,
        )
        assert launches == 1
        routes = decompose.stitch(plan, results)
        decompose.repair_boundary(plan, routes, seed=1, weights=w)
        decompose.rebalance_capacity(plan, routes)
        served = sorted(c for r in routes for c in r)
        assert served == list(range(1, 61))
        ev = decompose.evaluate_routes(plan, routes)
        assert ev["cap_excess"] == 0.0
        assert ev["distance"] >= plan.lower_bound - 1e-6

    def test_dense_parse_unchanged_below_threshold(self):
        from vrpms_tpu.io.cvrplib import parse_cvrplib

        coords, demands = synth_clustered_coords(12, 2, seed=4)
        text = _vrp_text(coords, demands, 50)
        a, _ = parse_cvrplib(text)
        b, meta = parse_cvrplib(text, max_dense_n=100)
        assert b is not None and "streamed" not in meta
        np.testing.assert_array_equal(
            np.asarray(a.durations), np.asarray(b.durations)
        )


# ---------------------------------------------------------------------------
# GA / ACO continuation schedules (the warm-seed satellites)
# ---------------------------------------------------------------------------


class TestContinuationSchedules:
    def test_ga_ramp_keeps_slot0_exact_and_perms_valid(self):
        import jax

        from vrpms_tpu.solvers.ga import continuation_perm_ramp

        n = 12
        warm = np.random.default_rng(0).permutation(np.arange(1, n + 1))
        warm = np.asarray(warm, dtype=np.int32)
        pop = continuation_perm_ramp(
            jax.random.key(0), 16, warm, "gather"
        )
        pop = np.asarray(pop)
        assert pop.shape == (16, n)
        assert np.array_equal(pop[0], warm)  # exploitation anchor
        # ... and ONLY slot 0: the mid/heavy groups must not waste
        # slots on further exact copies of the seed
        exact = [i for i in range(16) if np.array_equal(pop[i], warm)]
        assert exact == [0], exact
        for row in pop:
            assert sorted(row.tolist()) == list(range(1, n + 1))
        # the ramp grades perturbation: light clones nearer the seed
        # than the heavy diversity tail, on average
        ham = (pop != warm[None]).sum(axis=1)
        assert ham[1:4].mean() <= ham[12:].mean()

    def test_aco_continuation_predeposits_harder(self):
        from vrpms_tpu.core.cost import CostWeights
        from vrpms_tpu.io.synth import synth_cvrp
        from vrpms_tpu.solvers.aco import (
            ACOParams,
            CONTINUATION_DEPOSIT,
            WARM_DEPOSIT,
            _aco_init_fn,
        )
        import dataclasses
        import jax.numpy as jnp

        assert CONTINUATION_DEPOSIT > WARM_DEPOSIT
        inst = synth_cvrp(10, 2, seed=0)
        w = CostWeights.make()
        seed_perm = jnp.arange(1, 10, dtype=jnp.int32)
        params = dataclasses.replace(ACOParams(), n_iters=0, knn_k=0)
        tau_w = _aco_init_fn(params, 0, True, WARM_DEPOSIT)(
            inst, w, seed_perm
        )[0]
        tau_c = _aco_init_fn(params, 0, True, CONTINUATION_DEPOSIT)(
            inst, w, seed_perm
        )[0]
        # seed-tour edges carry strictly more pheromone under the
        # continuation pre-deposit; untouched edges stay equal
        diff = np.asarray(tau_c) - np.asarray(tau_w)
        assert diff.max() > 0
        assert diff.min() >= -1e-12

    def test_aco_continuation_solve_never_worse_than_seed(self):
        from vrpms_tpu.core.split import greedy_split_giant
        from vrpms_tpu.core.cost import CostWeights, exact_cost
        from vrpms_tpu.io.synth import synth_cvrp
        from vrpms_tpu.solvers.aco import ACOParams, solve_aco
        import jax.numpy as jnp

        inst = synth_cvrp(10, 2, seed=1)
        w = CostWeights.make()
        seed_perm = jnp.arange(1, 10, dtype=jnp.int32)
        res = solve_aco(
            inst,
            key=0,
            params=ACOParams(n_ants=8, n_iters=10),
            weights=w,
            init_perm=seed_perm,
            continuation=True,
        )
        _, seed_cost = exact_cost(
            greedy_split_giant(seed_perm, inst), inst, w
        )
        assert float(res.cost) <= float(seed_cost) + 1e-5
