"""Fused time-dependent delta-step kernel (kernels.sa_delta_td):
interpret-mode equivalence and state-integrity on CPU.

The TD kernel prices moves with POSITION-FROZEN factor weights (the
surrogate objective; kernels/sa_delta_td.py rationale), so unlike the
TW kernel there is no per-move cost identity to pin against the exact
evaluation — acceptance noise between resyncs is by design. What IS
exact, and what these tests pin:

  * tours transform EXACTLY like the XLA move reference (always-accept
    trajectories are decision-independent);
  * every maintained array re-derives exactly from the final tours —
    demands, and the R basis-leg arrays against the bf16 basis tables
    (this pins the per-rank junction-fix algebra);
  * the surrogate cost row is exactly sum_r fw * lgr + wcap * cape of
    the committed state (the kernel's own invariant);
  * the resync pass (_td_fw_fn) reprices committed tours with the TRUE
    timeline: its distance must match core.cost._td_eval up to the
    bf16 basis-leg rounding;
  * the solve-level driver returns an EXACTLY-priced champion
    (exact_cost of the giant), valid tours, and the gate admits only
    the classes the kernel models.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vrpms_tpu.core.cost import CostWeights, exact_cost
from vrpms_tpu.io.synth import synth_td
from vrpms_tpu.moves import knn_table
from vrpms_tpu.moves.moves import (
    move_batch_from_params,
    presample_move_params,
)
from vrpms_tpu.solvers.sa import (
    SAParams,
    _pow2_at_least,
    _td_fw_fn,
    _tile_interleave_r,
    initial_giants,
)

pytest.importorskip("jax.experimental.pallas")

from vrpms_tpu.kernels import sa_delta_td as K  # noqa: E402
from vrpms_tpu.kernels.sa_delta import _cap_excess_of, dp_init  # noqa: E402

W = CostWeights.make()


def _setup(n=22, v=4, batch=64, seed=5, knn_k=8, rank=1):
    inst = synth_td(n, v, seed=seed, rank=rank, t_slices=8)
    giants = initial_giants(jax.random.key(1), batch, inst, SAParams(), "onehot")
    b, length = giants.shape
    lhat = _pow2_at_least(length)
    nhat = 128
    rr = inst.td_rank
    assert rr == rank
    knn = knn_table(inst.durations[0], knn_k)
    kf = np.zeros((nhat, knn_k), np.float32)
    kf[: inst.n_nodes] = np.asarray(knn, np.float32)

    bas_np = np.zeros((rr, nhat, nhat), np.float32)
    bas_np[:, : inst.n_nodes, : inst.n_nodes] = np.asarray(inst.td_basis)
    bas_bf = jnp.asarray(bas_np, jnp.bfloat16)
    bas_f32 = bas_bf.astype(jnp.float32)
    d_cat = jnp.concatenate([bas_bf[r] for r in range(rr)], axis=1)

    gt_t = jnp.zeros((lhat, b), jnp.int32).at[:length].set(giants.T)
    dem_row = np.zeros((1, nhat), np.float32)
    dem_row[0, : inst.n_nodes] = np.asarray(inst.demands)
    dp_t = dp_init(gt_t, jnp.asarray(dem_row), tile_b=b, interpret=True)

    fw_t, lgr_t, dist0 = _td_fw_fn(length, b)(giants, inst, bas_f32)
    cap0 = float(np.asarray(inst.capacities)[0])
    scal = jnp.asarray([[cap0, float(W.cap)]], jnp.float32)
    cape0 = _cap_excess_of(gt_t, dp_t, scal[0, 0], lhat)
    cost0 = dist0 + scal[0, 1] * cape0
    return (
        inst, giants, length, lhat, rr, knn,
        d_cat, jnp.asarray(kf), bas_f32, fw_t, scal,
        gt_t, dp_t, lgr_t, cost0,
    )


def _state_checks(inst, length, rr, bas_f32, gt_t, dp_t, lgr_t):
    """gt must be valid tours; dp and every lgr rank-section must
    exactly re-derive from them (pins the R-section roll/fix algebra)."""
    b = gt_t.shape[1]
    g = np.asarray(gt_t[:length].T)
    for row in g:
        assert sorted(x for x in row if x) == list(
            range(1, inst.n_customers + 1)
        )
    dem = np.asarray(inst.demands)
    np.testing.assert_array_equal(np.asarray(dp_t[:length].T), dem[g])
    bas = np.asarray(bas_f32)
    prev, cur = g[:, :-1], g[:, 1:]
    # undo the tile-interleave (single tile in tests: sections adjacent)
    lgr = np.asarray(lgr_t)
    lhat = lgr.shape[0]
    for r in range(rr):
        sec = lgr[:, r * b : (r + 1) * b]
        np.testing.assert_array_equal(
            sec[: length - 1].T, bas[r][prev, cur]
        )
        assert (sec[length - 1 :] == 0).all()


class TestTdDeltaKernel:
    @pytest.mark.parametrize("rank", [1, 2])
    def test_always_accept_matches_xla_trajectory(self, rank):
        (inst, giants, L, lhat, rr, knn, d_cat, knn_f, bas_f32, fw_t,
         scal, gt_t, dp_t, lgr_t, cost0) = _setup(rank=rank)
        b = giants.shape[0]
        n_steps = 40
        i, r, mt, m, _u = presample_move_params(
            jax.random.key(3), b, L, n_steps, knn.shape[1]
        )
        u0 = jnp.zeros_like(_u)
        temps = jnp.full((1, n_steps), 1e6, jnp.float32)
        out = K.delta_td_block(
            gt_t, dp_t, lgr_t, cost0, gt_t, cost0,
            i, r, mt, m, u0, temps, d_cat, knn_f, fw_t, scal,
            length=L, rr=rr, tile_b=b, has_knn=True, interpret=True,
        )
        g_ref = giants
        for s in range(n_steps):
            g_ref = move_batch_from_params(
                i[s], r[s], mt[s], m[s], g_ref, knn, "gather"
            )
        assert (np.asarray(out[0][:L].T) == np.asarray(g_ref)).all()
        _state_checks(inst, L, rr, bas_f32, out[0], out[1], out[2])
        # the cost row must equal the kernel's own surrogate formula on
        # the final committed state: sum_r fw*lgr + wcap*cape
        fw = np.asarray(fw_t)
        lgr = np.asarray(out[2])
        dist = sum(
            (fw[:, r_ * b : (r_ + 1) * b] * lgr[:, r_ * b : (r_ + 1) * b]).sum(
                axis=0
            )
            for r_ in range(rr)
        )
        cape = np.asarray(
            _cap_excess_of(out[0], out[1], scal[0, 0], lhat)
        )[0]
        np.testing.assert_allclose(
            np.asarray(out[3][0]), dist + float(W.cap) * cape,
            rtol=1e-4, atol=1e-2,
        )

    def test_metropolis_never_accepts_worse_at_zero_temp(self):
        (inst, giants, L, lhat, rr, knn, d_cat, knn_f, bas_f32, fw_t,
         scal, gt_t, dp_t, lgr_t, cost0) = _setup(seed=9)
        b = giants.shape[0]
        n_steps = 60
        i, r, mt, m, u = presample_move_params(
            jax.random.key(7), b, L, n_steps, knn.shape[1]
        )
        u = jnp.maximum(u, 1e-9)
        temps = jnp.full((1, n_steps), 1e-6, jnp.float32)
        out = K.delta_td_block(
            gt_t, dp_t, lgr_t, cost0, gt_t, cost0,
            i, r, mt, m, u, temps, d_cat, knn_f, fw_t, scal,
            length=L, rr=rr, tile_b=b, has_knn=True, interpret=True,
        )
        _state_checks(inst, L, rr, bas_f32, out[0], out[1], out[2])
        assert (
            np.asarray(out[3][0]) <= np.asarray(cost0[0]) + 1e-3
        ).all()
        assert (np.asarray(out[5][0]) <= np.asarray(out[3][0]) + 1e-4).all()


class TestTdResync:
    def test_fw_refresh_matches_exact_timeline(self):
        from vrpms_tpu.core.cost import _td_eval

        (inst, giants, L, lhat, rr, knn, d_cat, knn_f, bas_f32, fw_t,
         scal, gt_t, dp_t, lgr_t, cost0) = _setup(seed=13)
        _fw, _lg, dist = _td_fw_fn(L, giants.shape[0])(giants, inst, bas_f32)
        # the resync distance must match the exact TD evaluation up to
        # the bf16 basis-leg rounding it deliberately shares with the
        # kernel (relative ~0.4% worst case per leg)
        for row in range(4):
            bd = _td_eval(giants[row], inst)
            np.testing.assert_allclose(
                float(dist[0, row]), float(bd.distance), rtol=1.5e-2
            )

    def test_tile_interleave_roundtrip(self):
        x = jnp.arange(2 * 3 * 8, dtype=jnp.float32).reshape(2, 3, 8)
        y = _tile_interleave_r(x, 4)  # two tiles of 4 lanes
        assert y.shape == (2, 24)
        # tile 0 columns: sections r=0..2 of lanes 0..3, then tile 1
        np.testing.assert_array_equal(
            np.asarray(y[:, :4]), np.asarray(x[:, 0, :4])
        )
        np.testing.assert_array_equal(
            np.asarray(y[:, 4:8]), np.asarray(x[:, 1, :4])
        )
        np.testing.assert_array_equal(
            np.asarray(y[:, 12:16]), np.asarray(x[:, 0, 4:])
        )


class TestSolveSaDeltaTd:
    def test_solve_level_driver(self, monkeypatch):
        monkeypatch.setenv("VRPMS_DELTA_INTERPRET", "1")
        from vrpms_tpu.solvers.sa import solve_sa_delta

        inst = synth_td(18, 3, seed=2, t_slices=8)
        res = solve_sa_delta(
            inst, key=4, params=SAParams(n_chains=128, n_iters=400)
        )
        row = [int(x) for x in np.asarray(res.giant) if x]
        assert sorted(row) == list(range(1, inst.n_customers + 1))
        # the returned cost is the exact re-evaluation of the champion
        _, want = exact_cost(res.giant, inst, W)
        assert np.isclose(float(res.cost), float(want), rtol=1e-6)

    def test_gate_size_boundary(self):
        # round 5 raised the size gate from 512 to 1024 (the X series
        # tops out at n=1001); past it the fast path must refuse
        from vrpms_tpu.io.synth import synth_cvrp
        from vrpms_tpu.kernels.sa_delta import _PALLAS_OK
        from vrpms_tpu.solvers.sa import _delta_supported

        if not _PALLAS_OK:
            pytest.skip("pallas unavailable")
        assert _delta_supported(synth_cvrp(1001, 43, seed=1), W, "pallas")
        assert not _delta_supported(synth_cvrp(1100, 43, seed=1), W, "pallas")

    def test_td_gate_is_512(self):
        # the TD surrogate path keeps the ORIGINAL 512 bound: the shared
        # delta gate admits untimed instances to 1024, but TD above 512
        # has never been hardware-validated (ADVICE round 5)
        from vrpms_tpu.kernels.sa_delta import _PALLAS_OK
        from vrpms_tpu.solvers.sa import _delta_supported

        if not _PALLAS_OK:
            pytest.skip("pallas unavailable")
        assert _delta_supported(synth_td(500, 20, seed=1, t_slices=8), W, "pallas")
        assert not _delta_supported(
            synth_td(600, 20, seed=1, t_slices=8), W, "pallas"
        )

    def test_gate_classes(self):
        from vrpms_tpu.core import make_instance
        from vrpms_tpu.kernels.sa_delta import _PALLAS_OK
        from vrpms_tpu.solvers.sa import _delta_supported

        if not _PALLAS_OK:
            pytest.skip("pallas unavailable")
        inst = synth_td(20, 3, seed=1, t_slices=8)
        assert _delta_supported(inst, W, "pallas")
        # full-rank (unfactorizable) TD profiles fall back
        rng = np.random.default_rng(0)
        d0 = np.asarray(inst.durations[0])
        slices = np.stack([
            d0 * rng.uniform(0.8, 1.2, size=d0.shape) for _ in range(6)
        ])
        slices = (slices + np.swapaxes(slices, 1, 2)) / 2  # keep symmetric
        full = make_instance(
            slices,
            demands=np.asarray(inst.demands),
            capacities=np.asarray(inst.capacities).tolist(),
            slice_axis="first",
            slice_minutes=60.0,
        )
        assert full.td_rank == 0 and not _delta_supported(full, W, "pallas")
        # an asymmetric slice falls back even when slice 0 is symmetric
        bad = np.stack([d0, d0 * 1.1])
        bad[1, 0, 1] += 5.0
        asym = make_instance(
            bad,
            demands=np.asarray(inst.demands),
            capacities=np.asarray(inst.capacities).tolist(),
            slice_axis="first",
            slice_minutes=60.0,
        )
        assert not _delta_supported(asym, W, "pallas")
