"""Heterogeneous-fleet correctness across GA/ACO/BF (VERDICT round-1 #4).

The reference parses per-vehicle `capacities` (reference
api/parameters.py:11); SA's giant-tour path always priced them exactly
(routes bind to vehicles positionally), but the permutation-genome
solvers' split shortcuts assumed capacities[0]. These tests pin the
het-aware behavior: per-vehicle greedy split, per-round optimal-split
DP, vehicle-aligned route reconstruction, exact-giant fitness dispatch
(Instance.het_fleet), and the end-to-end service contract.
"""

import itertools

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from vrpms_tpu.core import make_instance
from vrpms_tpu.core.cost import CostWeights, exact_cost
from vrpms_tpu.core.encoding import is_valid_giant, routes_from_giant
from vrpms_tpu.core.split import (
    greedy_split_giant,
    optimal_split_cost,
    optimal_split_routes,
)
from vrpms_tpu.solvers import solve_vrp_bf
from vrpms_tpu.solvers.aco import ACOParams, solve_aco
from vrpms_tpu.solvers.ga import GAParams, solve_ga
from vrpms_tpu.solvers.sa import SAParams, solve_sa


def het_instance(rng, n=8, caps=(9.0, 5.0, 3.0)):
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    demands = [0.0] + [float(x) for x in rng.integers(1, 4, n - 1)]
    return make_instance(d, demands=demands, capacities=list(caps))


def python_het_split_optimum(perm, d, demands, caps):
    """Exact DP oracle: serve perm's prefix with vehicles 0..r in order
    (any vehicle may stay empty), per-vehicle capacity bounds."""
    n = len(perm)

    def route_cost(i, j):  # serve perm[i:j] as one route
        path = [0] + list(perm[i:j]) + [0]
        return sum(d[a, b] for a, b in zip(path[:-1], path[1:]))

    INF = float("inf")
    vals = [0.0] + [INF] * n  # vals[j]: best cost serving perm[:j]
    for cap in caps:
        nxt = list(vals)
        for j in range(1, n + 1):
            for i in range(j):
                load = sum(demands[c] for c in perm[i:j])
                if load <= cap and vals[i] + route_cost(i, j) < nxt[j]:
                    nxt[j] = vals[i] + route_cost(i, j)
        vals = nxt
    return vals[n]


class TestHetSplit:
    def test_greedy_split_uses_per_vehicle_capacities(self, rng):
        inst = het_instance(rng, n=9, caps=(8.0, 4.0, 2.0, 2.0))
        caps = np.asarray(inst.capacities)
        demands = np.asarray(inst.demands)
        for seed in range(5):
            perm = jnp.asarray(
                np.random.default_rng(seed).permutation(np.arange(1, 9)),
                jnp.int32,
            )
            giant = greedy_split_giant(perm, inst)
            assert is_valid_giant(np.asarray(giant), 8, 4)
            # python twin of the per-vehicle greedy rule
            loads = [0.0] * len(caps)
            r = 0
            expected_routes = [[] for _ in caps]
            for k, c in enumerate(np.asarray(perm)):
                dk = float(demands[c])
                q = caps[min(r, len(caps) - 1)]
                if k > 0 and loads[min(r, len(caps) - 1)] + dk > q:
                    r = min(r + 1, len(caps) - 1)
                    loads[r] = dk
                else:
                    loads[min(r, len(caps) - 1)] += dk
                expected_routes[min(r, len(caps) - 1)].append(int(c))
            assert routes_from_giant(giant) == expected_routes

    def test_optimal_split_matches_python_dp(self, rng):
        inst = het_instance(rng, n=8, caps=(7.0, 5.0, 3.0))
        d = np.asarray(inst.durations[0])
        demands = np.asarray(inst.demands)
        for seed in range(6):
            perm = np.random.default_rng(100 + seed).permutation(
                np.arange(1, 8)
            )
            want = python_het_split_optimum(
                list(perm), d, demands, np.asarray(inst.capacities)
            )
            got = float(optimal_split_cost(jnp.asarray(perm, jnp.int32), inst))
            if want == float("inf"):
                assert got >= 1e8
                continue
            np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_reconstruction_vehicle_aligned(self, rng):
        # spans must land on the vehicle whose capacity bound the DP
        # applied — positional giant pricing must see zero excess
        inst = het_instance(rng, n=8, caps=(7.0, 5.0, 3.0))
        demands = np.asarray(inst.demands)
        caps = np.asarray(inst.capacities)
        for seed in range(6):
            perm = np.random.default_rng(200 + seed).permutation(
                np.arange(1, 8)
            )
            cost = float(optimal_split_cost(jnp.asarray(perm, jnp.int32), inst))
            if cost >= 1e8:
                continue
            routes = optimal_split_routes(jnp.asarray(perm, jnp.int32), inst)
            assert len(routes) == len(caps)  # vehicle-aligned, empties kept
            for r, route in enumerate(routes):
                assert sum(demands[c] for c in route) <= caps[r] + 1e-6


class TestHetBF:
    def test_bf_matches_itertools_het(self, rng):
        # caps comfortably cover the worst-case total demand (6 x 3)
        inst = het_instance(rng, n=7, caps=(9.0, 7.0, 5.0))
        d = np.asarray(inst.durations[0])
        demands = np.asarray(inst.demands)
        caps = np.asarray(inst.capacities)
        best = float("inf")
        for perm in itertools.permutations(range(1, 7)):
            best = min(
                best, python_het_split_optimum(list(perm), d, demands, caps)
            )
        res = solve_vrp_bf(inst)
        np.testing.assert_allclose(float(res.cost), best, rtol=1e-5)
        assert float(res.breakdown.cap_excess) == 0.0
        # the decoded giant's positional loads respect each vehicle
        for r, route in enumerate(routes_from_giant(res.giant)):
            assert sum(demands[c] for c in route) <= caps[r] + 1e-6


class TestHetMetaheuristics:
    @pytest.mark.parametrize("solver", ["ga", "aco", "sa"])
    def test_feasible_per_vehicle_and_never_mispriced(self, rng, solver):
        inst = het_instance(rng, n=9, caps=(10.0, 6.0, 4.0))
        assert inst.het_fleet
        w = CostWeights.make()
        if solver == "ga":
            res = solve_ga(inst, key=0, params=GAParams(population=64, generations=60))
        elif solver == "aco":
            res = solve_aco(inst, key=0, params=ACOParams(n_ants=32, n_iters=60))
        else:
            res = solve_sa(inst, key=0, params=SAParams(n_chains=64, n_iters=2000))
        # the reported cost is the EXACT positional pricing of the giant
        np.testing.assert_allclose(
            float(res.cost), float(exact_cost(res.giant, inst, w)[1]), rtol=1e-6
        )
        # an easy instance (total demand 8..24 vs fleet 20) must come
        # back per-vehicle feasible — mispricing against capacities[0]
        # would show up as hidden excess here
        assert float(res.breakdown.cap_excess) == 0.0
        demands = np.asarray(inst.demands)
        caps = np.asarray(inst.capacities)
        for r, route in enumerate(routes_from_giant(res.giant)):
            assert sum(demands[c] for c in route) <= caps[r] + 1e-6
