"""Loader tests: hand-written fixtures in both standard formats."""

import numpy as np
import pytest

from vrpms_tpu.io import (
    gap_percent,
    parse_cvrplib,
    parse_solomon,
    synth_cvrp,
    synth_tsp,
    synth_vrptw,
)
from vrpms_tpu.solvers import solve_sa
from vrpms_tpu.solvers.sa import SAParams

CVRP_TEXT = """NAME : TINY-n5-k2
COMMENT : hand-written fixture
TYPE : CVRP
DIMENSION : 5
EDGE_WEIGHT_TYPE : EUC_2D
CAPACITY : 10
NODE_COORD_SECTION
 1 0 0
 2 3 0
 3 3 4
 4 0 4
 5 6 8
DEMAND_SECTION
 1 0
 2 4
 3 5
 4 6
 5 3
DEPOT_SECTION
 1
 -1
EOF
"""

SOLOMON_TEXT = """TINY1

VEHICLE
NUMBER     CAPACITY
   3         50

CUSTOMER
CUST NO.  XCOORD.   YCOORD.    DEMAND   READY TIME   DUE DATE   SERVICE TIME
    0      10         10          0          0       500          0
    1      15         10         10         50       150         10
    2      10         20         20          0       100         10
    3       5          5         15        100       300         10
"""


class TestCVRPLIB:
    def test_parse_fields(self):
        inst, meta = parse_cvrplib(CVRP_TEXT)
        assert meta["name"] == "TINY-n5-k2"
        assert inst.n_nodes == 5
        assert inst.n_vehicles == 2  # from -k2 suffix
        assert float(inst.capacities[0]) == 10.0
        np.testing.assert_allclose(np.asarray(inst.demands), [0, 4, 5, 6, 3])
        # nint(euclid): node1->node2 = 3, node2->node3 = 4, node1->node5 = 10
        d = np.asarray(inst.durations[0])
        assert d[0, 1] == 3 and d[1, 2] == 4 and d[0, 4] == 10

    def test_unrounded(self):
        inst, _ = parse_cvrplib(CVRP_TEXT, round_nint=False)
        d = np.asarray(inst.durations[0])
        np.testing.assert_allclose(d[0, 2], 5.0)

    def test_explicit_matrix(self):
        text = """NAME : EXP3
TYPE : CVRP
DIMENSION : 3
EDGE_WEIGHT_TYPE : EXPLICIT
EDGE_WEIGHT_FORMAT : FULL_MATRIX
CAPACITY : 5
EDGE_WEIGHT_SECTION
0 2 9
2 0 4
9 4 0
DEMAND_SECTION
1 0
2 1
3 2
EOF
"""
        inst, _ = parse_cvrplib(text)
        d = np.asarray(inst.durations[0])
        assert d[0, 2] == 9 and d[2, 1] == 4

    def test_solvable(self):
        inst, _ = parse_cvrplib(CVRP_TEXT)
        res = solve_sa(inst, key=0, params=SAParams(n_chains=32, n_iters=800))
        assert float(res.breakdown.cap_excess) == 0.0


class TestSolomon:
    def test_parse(self):
        inst, meta = parse_solomon(SOLOMON_TEXT)
        assert inst.n_nodes == 4
        assert inst.n_vehicles == 3
        assert float(inst.capacities[0]) == 50.0
        assert inst.has_tw
        np.testing.assert_allclose(np.asarray(inst.ready), [0, 50, 0, 100])
        np.testing.assert_allclose(np.asarray(inst.due), [500, 150, 100, 300])
        # service[0] forced to 0 at the depot
        np.testing.assert_allclose(np.asarray(inst.service), [0, 10, 10, 10])
        # truncated to 1dp: dist(0,1) = 5.0, dist(0,2) = 10.0
        d = np.asarray(inst.durations[0])
        assert d[0, 1] == 5.0 and d[0, 2] == 10.0

    def test_solvable_feasible(self):
        inst, _ = parse_solomon(SOLOMON_TEXT)
        res = solve_sa(inst, key=0, params=SAParams(n_chains=64, n_iters=2000))
        assert float(res.breakdown.tw_lateness) == 0.0
        assert float(res.breakdown.cap_excess) == 0.0


class TestSynth:
    def test_deterministic(self):
        a = synth_cvrp(30, 4, seed=7)
        b = synth_cvrp(30, 4, seed=7)
        np.testing.assert_array_equal(np.asarray(a.durations), np.asarray(b.durations))
        assert a.n_vehicles == 4

    def test_vrptw_has_tw(self):
        inst = synth_vrptw(20, 4, seed=1)
        assert inst.has_tw
        assert float(inst.due[0]) == 1000.0

    def test_tsp(self):
        inst = synth_tsp(16, seed=2)
        assert inst.n_vehicles == 1 and inst.n_customers == 15

    def test_gap(self):
        assert gap_percent(102.0, 100.0) == pytest.approx(2.0)
        with pytest.raises(ValueError):
            gap_percent(1.0, 0.0)
