"""API-contract tests against the in-memory store (SURVEY.md §4 item 4).

Replays the reference's request/response shapes end-to-end over real
HTTP: camelCase keys, error accumulation, the fail/success envelopes,
result asymmetry (VRP vehicles/durationMax/durationSum vs TSP
vehicle/duration), VRP-only location filtering on save, CORS preflight
on VRP GA only.
"""

import json
import threading
import urllib.request
import urllib.error

import numpy as np
import pytest

import store.memory as mem
from service.app import serve

# the islands option drives shard_map-built solvers; on old-jax
# containers (no jax.shard_map) those requests can only fail in the
# solver — environment-pre-broken, so the islands cases skip there
# (see tests/test_islands.py)


def _has_shard_map():
    import jax

    return hasattr(jax, "shard_map")


needs_shard_map = pytest.mark.skipif(
    not _has_shard_map(),
    reason="jax.shard_map unavailable (old jax); islands need it",
)


@pytest.fixture(scope="module")
def server():
    import os

    os.environ["VRPMS_STORE"] = "memory"
    srv = serve(port=0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()


@pytest.fixture(autouse=True)
def seeded():
    mem.reset()
    rng = np.random.default_rng(11)
    pts = rng.uniform(0, 100, size=(7, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    locations = [
        {"id": i, "name": f"loc{i}", "demand": 2 if i else 0} for i in range(7)
    ]
    mem.seed_locations("locs1", locations)
    mem.seed_durations("durs1", d.tolist())
    mem.register_token("tok-alice", "alice@example.com")
    yield


def post(base, path, body):
    status, parsed, _ = post_h(base, path, body)
    return status, parsed


def post_h(base, path, body):
    """POST returning (status, parsed_body, headers) for header checks."""
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read()), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), e.headers


def get(base, path):
    with urllib.request.urlopen(base + path) as resp:
        return resp.status, resp.read().decode()


def vrp_body(**over):
    body = {
        "solutionName": "s1",
        "solutionDescription": "test",
        "locationsKey": "locs1",
        "durationsKey": "durs1",
        "capacities": [6, 6, 6],
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": 1,
        "iterationCount": 500,
    }
    body.update(over)
    return body


def tsp_body(**over):
    body = {
        "solutionName": "t1",
        "solutionDescription": "test",
        "locationsKey": "locs1",
        "durationsKey": "durs1",
        "customers": [1, 2, 3, 4, 5, 6],
        "startNode": 0,
        "startTime": 0,
        "seed": 1,
        "iterationCount": 500,
    }
    body.update(over)
    return body


ALL_ROUTES = [
    "/api/vrp/ga",
    "/api/vrp/sa",
    "/api/vrp/aco",
    "/api/vrp/bf",
    "/api/tsp/ga",
    "/api/tsp/sa",
    "/api/tsp/aco",
    "/api/tsp/bf",
]


class TestBanners:
    def test_health(self, server):
        status, text = get(server, "/api")
        assert status == 200 and text == "Hello!"

    def test_solver_banners(self, server):
        for route in ALL_ROUTES:
            status, text = get(server, route)
            assert status == 200
            assert text.startswith("Hi, this is the")

    def test_unknown_404(self, server):
        with pytest.raises(urllib.error.HTTPError) as e:
            get(server, "/api/nope")
        assert e.value.code == 404


class TestErrorEnvelope:
    def test_missing_params_accumulate(self, server):
        status, resp = post(server, "/api/vrp/sa", {})
        assert status == 400
        assert resp["success"] is False
        missing = {e["reason"] for e in resp["errors"]}
        assert "'solutionName' was not provided" in missing
        assert "'capacities' was not provided" in missing
        assert all(e["what"] == "Missing parameter" for e in resp["errors"])

    def test_vrp_ga_requires_algo_params(self, server):
        body = vrp_body()
        del body["iterationCount"]
        status, resp = post(server, "/api/vrp/ga", body)
        assert status == 400
        reasons = {e["reason"] for e in resp["errors"]}
        assert "'multiThreaded' was not provided" in reasons
        assert "'randomPermutationCount' was not provided" in reasons
        assert "'iterationCount' was not provided" in reasons

    def test_bad_locations_key(self, server):
        status, resp = post(server, "/api/vrp/sa", vrp_body(locationsKey="nope"))
        assert status == 400
        assert resp["errors"][0]["what"] == "Database read error"
        assert "No location set found" in resp["errors"][0]["reason"]

    def test_bf_too_large_is_solver_error(self, server):
        # past the branch-and-bound's 34-customer bound (11-34 now
        # dispatch to the exact B&B instead of erroring)
        rng = np.random.default_rng(0)
        n = 41
        d = rng.uniform(1, 10, size=(n, n))
        mem.seed_locations("big", [{"id": i} for i in range(n)])
        mem.seed_durations("bigd", d.tolist())
        status, resp = post(
            server,
            "/api/vrp/bf",
            vrp_body(locationsKey="big", durationsKey="bigd", capacities=[99] * 3),
        )
        assert status == 400
        assert resp["errors"][0]["what"] == "Solver error"

    def test_non_finite_or_negative_matrix_rejected(self, server):
        n = 7
        bad = [[0.0] * n for _ in range(n)]
        bad[1][2] = float("nan")
        mem.seed_durations("durs-nan", bad)
        status, resp = post(
            server, "/api/vrp/sa", vrp_body(durationsKey="durs-nan")
        )
        assert status == 400
        assert any("finite" in e["reason"] for e in resp["errors"])
        neg = [[0.0] * n for _ in range(n)]
        neg[2][3] = -5.0
        mem.seed_durations("durs-neg", neg)
        status, resp = post(
            server, "/api/tsp/sa", tsp_body(durationsKey="durs-neg")
        )
        assert status == 400
        assert any("non-negative" in e["reason"] for e in resp["errors"])
        # bad entries confined to EXCLUDED locations must not reject:
        # inf rows are a legitimate unreachable-node convention
        status, resp = post(
            server,
            "/api/vrp/sa",
            vrp_body(durationsKey="durs-nan", ignoredCustomers=[1]),
        )
        assert status == 200, resp

    def test_matrix_shape_mismatch(self, server):
        mem.seed_durations("badshape", [[0, 1], [1, 0]])
        status, resp = post(server, "/api/vrp/sa", vrp_body(durationsKey="badshape"))
        assert status == 400
        assert resp["errors"][0]["what"] == "Data error"

    def test_non_numeric_fields_get_envelope_not_crash(self, server):
        # Conversion failures must produce the 400 envelope, never a
        # dropped connection.
        status, resp = post(server, "/api/vrp/sa", vrp_body(capacities=["abc", 6]))
        assert status == 400
        assert resp["errors"][0]["what"] == "Data error"
        status, resp = post(server, "/api/vrp/sa", vrp_body(seed="xyz"))
        assert status == 400
        assert resp["errors"][0]["what"] == "Data error"
        status, resp = post(server, "/api/tsp/sa", tsp_body(startTime="9am"))
        assert status == 400
        assert resp["errors"][0]["what"] == "Data error"

    def test_tsp_duplicate_customers_deduped(self, server):
        status, resp = post(server, "/api/tsp/sa", tsp_body(customers=[3, 3, 5, 5]))
        assert status == 200
        assert sorted(resp["message"]["vehicle"][1:-1]) == [3, 5]


class TestVRPSolve:
    @pytest.mark.parametrize("route", ["/api/vrp/sa", "/api/vrp/bf", "/api/vrp/aco"])
    def test_solves_and_covers_all_customers(self, server, route):
        status, resp = post(server, route, vrp_body())
        assert status == 200, resp
        assert resp["success"] is True
        msg = resp["message"]
        # the exact endpoint ADDS its proof certificate (round 5) and
        # the solution cache its hit marker (round 6); the reference
        # keys stay byte-identical
        want = {"durationMax", "durationSum", "vehicles", "cacheHit"}
        if route.endswith("/bf"):
            want = want | {"exact"}
        assert set(msg) == want
        visited = [c for v in msg["vehicles"] for c in v["tour"][1:-1]]
        assert sorted(visited) == [1, 2, 3, 4, 5, 6]
        for v in msg["vehicles"]:
            assert v["tour"][0] == 0 and v["tour"][-1] == 0
            assert v["load"] <= v["capacity"] + 1e-6
        assert msg["durationMax"] <= msg["durationSum"] + 1e-6

    def test_ga_honors_reference_params(self, server):
        status, resp = post(
            server,
            "/api/vrp/ga",
            vrp_body(multiThreaded=True, randomPermutationCount=64, iterationCount=100),
        )
        assert status == 200, resp
        msg = resp["message"]
        visited = [c for v in msg["vehicles"] for c in v["tour"][1:-1]]
        assert sorted(visited) == [1, 2, 3, 4, 5, 6]

    def test_ignored_customers_excluded(self, server):
        status, resp = post(
            server, "/api/vrp/sa", vrp_body(ignoredCustomers=[3], completedCustomers=[5])
        )
        assert status == 200
        visited = [c for v in resp["message"]["vehicles"] for c in v["tour"][1:-1]]
        assert sorted(visited) == [1, 2, 4, 6]

    def test_sa_matches_bf_on_seeded_instance(self, server):
        _, sa = post(server, "/api/vrp/sa", vrp_body(iterationCount=4000))
        _, bf = post(server, "/api/vrp/bf", vrp_body())
        assert sa["message"]["durationSum"] <= bf["message"]["durationSum"] * 1.05

    def test_local_search_polishes_and_never_worsens(self, server):
        plain_body = vrp_body(iterationCount=50, populationSize=8)
        _, plain = post(server, "/api/vrp/sa", plain_body)
        status, pol = post(
            server,
            "/api/vrp/sa",
            vrp_body(
                iterationCount=50,
                populationSize=8,
                localSearch=True,
                includeStats=True,
            ),
        )
        assert status == 200, pol
        assert pol["message"]["stats"]["localSearch"] is True
        assert (
            pol["message"]["durationSum"]
            <= plain["message"]["durationSum"] + 1e-6
        )
        visited = [c for v in pol["message"]["vehicles"] for c in v["tour"][1:-1]]
        assert sorted(visited) == [1, 2, 3, 4, 5, 6]

    @needs_shard_map
    def test_islands_sa_solves_over_virtual_mesh(self, server):
        """islands rides the conftest's 8 virtual CPU devices: the
        sharded ring-migration program must serve the same contract."""
        status, resp = post(
            server,
            "/api/vrp/sa",
            vrp_body(
                islands=4,
                iterationCount=300,
                populationSize=16,
                migrateEvery=50,
                migrants=2,
                includeStats=True,
            ),
        )
        assert status == 200, resp
        msg = resp["message"]
        assert msg["stats"]["islands"] == 4
        visited = [c for v in msg["vehicles"] for c in v["tour"][1:-1]]
        assert sorted(visited) == [1, 2, 3, 4, 5, 6]

    @needs_shard_map
    def test_islands_ga_solves_and_clamps(self, server):
        status, resp = post(
            server,
            "/api/vrp/ga",
            vrp_body(
                multiThreaded=True,
                randomPermutationCount=24,
                iterationCount=60,
                islands=999,  # more than attached devices: clamped
                includeStats=True,
            ),
        )
        assert status == 200, resp
        msg = resp["message"]
        assert 1 <= msg["stats"]["islands"] <= 8
        visited = [c for v in msg["vehicles"] for c in v["tour"][1:-1]]
        assert sorted(visited) == [1, 2, 3, 4, 5, 6]

    def test_islands_rejects_nonsense_migration_options(self, server):
        """Negative migrateEvery would silently run ZERO iterations in
        the sharded solvers; the boundary must reject it instead."""
        for bad in (
            {"islands": 2, "migrateEvery": -7},
            {"islands": 2, "migrants": -2},
            {"islands": -3},
        ):
            status, resp = post(server, "/api/vrp/sa", vrp_body(**bad))
            assert status == 400, (bad, resp)
            assert resp["success"] is False
            assert any("positive integer" in e["reason"] for e in resp["errors"])

    def test_local_search_on_tsp(self, server):
        status, resp = post(
            server, "/api/tsp/sa", tsp_body(localSearch=32, includeStats=True)
        )
        assert status == 200, resp
        assert resp["message"]["stats"]["localSearch"] is True
        assert sorted(resp["message"]["vehicle"][1:-1]) == [1, 2, 3, 4, 5, 6]

    def test_local_search_pool_polish(self, server):
        status, resp = post(
            server,
            "/api/vrp/ga",
            vrp_body(
                multiThreaded=False,
                randomPermutationCount=24,
                iterationCount=40,
                localSearch=True,
                localSearchPool=6,
                includeStats=True,
            ),
        )
        assert status == 200, resp
        msg = resp["message"]
        assert msg["stats"]["localSearch"] is True
        visited = [c for v in msg["vehicles"] for c in v["tour"][1:-1]]
        assert sorted(visited) == [1, 2, 3, 4, 5, 6]

    def test_ils_rounds_solves_and_reports(self, server):
        status, resp = post(
            server,
            "/api/vrp/sa",
            vrp_body(iterationCount=400, populationSize=16, ilsRounds=2,
                     includeStats=True),
        )
        assert status == 200, resp
        msg = resp["message"]
        assert msg["stats"]["ilsRounds"] == 2
        visited = [c for v in msg["vehicles"] for c in v["tour"][1:-1]]
        assert sorted(visited) == [1, 2, 3, 4, 5, 6]

    @needs_shard_map
    def test_ils_composes_with_islands(self, server):
        status, resp = post(
            server,
            "/api/vrp/sa",
            vrp_body(
                iterationCount=400,
                populationSize=16,
                ilsRounds=2,
                islands=4,
                migrateEvery=100,
                includeStats=True,
            ),
        )
        assert status == 200, resp
        msg = resp["message"]
        assert msg["stats"]["ilsRounds"] == 2
        assert msg["stats"]["islands"] == 4
        visited = [c for v in msg["vehicles"] for c in v["tour"][1:-1]]
        assert sorted(visited) == [1, 2, 3, 4, 5, 6]

    def test_ils_reseed_option(self, server):
        for mode in ("ruin", "moves"):
            status, resp = post(
                server,
                "/api/vrp/sa",
                vrp_body(iterationCount=200, populationSize=16, ilsRounds=2,
                         ilsReseed=mode, includeStats=True),
            )
            assert status == 200, resp
            visited = [c for v in resp["message"]["vehicles"]
                       for c in v["tour"][1:-1]]
            assert sorted(visited) == [1, 2, 3, 4, 5, 6]
        status, resp = post(
            server, "/api/vrp/sa",
            vrp_body(ilsRounds=2, ilsReseed="bogus"),
        )
        assert status == 400
        assert any("ilsReseed" in e["reason"] for e in resp["errors"])

    def test_ils_rounds_zero_means_off(self, server):
        # explicit 0 disables ILS (plain SA), like timeLimit's 0 —
        # not a Solver-error envelope
        status, resp = post(
            server,
            "/api/vrp/sa",
            vrp_body(iterationCount=200, populationSize=16, ilsRounds=0,
                     includeStats=True),
        )
        assert status == 200, resp
        msg = resp["message"]
        assert "ilsRounds" not in msg["stats"]
        visited = [c for v in msg["vehicles"] for c in v["tour"][1:-1]]
        assert sorted(visited) == [1, 2, 3, 4, 5, 6]

    def test_bare_local_search_pool_enables_polish(self, server):
        # an explicit localSearchPool > 1 without localSearch clearly
        # intends the polish: it runs with the default budget
        status, resp = post(
            server,
            "/api/vrp/sa",
            vrp_body(iterationCount=200, populationSize=16,
                     localSearchPool=4, includeStats=True),
        )
        assert status == 200, resp
        assert resp["message"]["stats"]["localSearch"] is True
        # ... but an explicit localSearch: false still wins
        status, resp = post(
            server,
            "/api/vrp/sa",
            vrp_body(iterationCount=200, populationSize=16,
                     localSearch=False, localSearchPool=4,
                     includeStats=True),
        )
        assert status == 200, resp
        assert resp["message"]["stats"]["localSearch"] is False

    def test_bf_honors_time_limit(self, server):
        # BF accepts timeLimit like every other solver (chunked
        # enumeration); a tiny instance finishes inside the first chunk
        # so the result stays exact and complete
        status, resp = post(
            server, "/api/vrp/bf", vrp_body(timeLimit=30, includeStats=True)
        )
        assert status == 200, resp
        msg = resp["message"]
        visited = [c for v in msg["vehicles"] for c in v["tour"][1:-1]]
        assert sorted(visited) == [1, 2, 3, 4, 5, 6]

    def test_bf_dispatches_to_bnb_beyond_enumeration(self, server):
        # 12 customers is past enumeration's 10-customer bound: the BF
        # endpoint must dispatch to the exact branch-and-bound and the
        # served optimum must match a direct proven solve
        rng = np.random.default_rng(5)
        pts = rng.uniform(0, 100, size=(13, 2))
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        mem.seed_locations(
            "locs_big",
            [{"id": i, "name": f"b{i}", "demand": 3 if i else 0} for i in range(13)],
        )
        mem.seed_durations("durs_big", d.tolist())
        status, resp = post(
            server,
            "/api/vrp/bf",
            vrp_body(
                locationsKey="locs_big",
                durationsKey="durs_big",
                capacities=[12, 12, 12, 12],
                startTimes=[0, 0, 0, 0],
                timeLimit=60,
            ),
        )
        assert status == 200, resp
        msg = resp["message"]
        visited = sorted(c for v in msg["vehicles"] for c in v["tour"][1:-1])
        assert visited == list(range(1, 13))
        from vrpms_tpu.core import make_instance
        from vrpms_tpu.solvers.exact import solve_cvrp_bnb

        inst = make_instance(d, demands=[0] + [3] * 12, capacities=[12] * 4)
        want, proven, _ = solve_cvrp_bnb(inst, time_limit_s=60)
        assert proven
        assert abs(msg["durationSum"] - float(want.breakdown.distance)) < 1e-2

    def test_bf_infeasible_instance_returns_best_effort(self, server):
        # 12 customers whose total demand exceeds the whole fleet: the
        # branch-and-bound has NO capacity-feasible solution (it raises),
        # so the endpoint must fall back to enumeration's penalized
        # best-effort result instead of a Solver error (ADVICE round 3)
        rng = np.random.default_rng(6)
        pts = rng.uniform(0, 100, size=(13, 2))
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        mem.seed_locations(
            "locs_over",
            [{"id": i, "name": f"o{i}", "demand": 9 if i else 0} for i in range(13)],
        )
        mem.seed_durations("durs_over", d.tolist())
        status, resp = post(
            server,
            "/api/vrp/bf",
            vrp_body(
                locationsKey="locs_over",
                durationsKey="durs_over",
                capacities=[10, 10],  # 2 * 10 < 12 * 9 demand
                startTimes=[0, 0],
                timeLimit=5,
            ),
        )
        assert status == 200, resp
        visited = sorted(
            c for v in resp["message"]["vehicles"] for c in v["tour"][1:-1]
        )
        assert visited == list(range(1, 13))

    @needs_shard_map
    def test_aco_islands_and_pool(self, server):
        # ACO honors islands (per-device colonies, elite ring) and
        # localSearchPool (per-island champions polished)
        status, resp = post(
            server,
            "/api/vrp/aco",
            vrp_body(iterationCount=60, populationSize=16, islands=4,
                     localSearchPool=4, includeStats=True),
        )
        assert status == 200, resp
        msg = resp["message"]
        assert msg["stats"]["islands"] == 4
        assert msg["stats"]["localSearch"] is True
        visited = [c for v in msg["vehicles"] for c in v["tour"][1:-1]]
        assert sorted(visited) == [1, 2, 3, 4, 5, 6]

    def test_aco_warm_start(self, server):
        # a checkpoint written by one solve warms the next ACO solve
        # (colony incumbent + pheromone head start), islands included
        body = vrp_body(solutionName="warm-aco", iterationCount=200,
                        populationSize=16, warmStart=True, auth="tok-alice")
        status, first = post(server, "/api/vrp/sa", body)
        assert status == 200, first
        status, resp = post(
            server,
            "/api/vrp/aco",
            vrp_body(solutionName="warm-aco", iterationCount=30,
                     populationSize=8, warmStart=True, auth="tok-alice",
                     includeStats=True),
        )
        assert status == 200, resp
        msg = resp["message"]
        assert msg["stats"]["warmStart"] is True
        # the warm incumbent keeps ACO near the checkpoint quality even
        # at a tiny budget (exact parity isn't guaranteed: the warm
        # order re-splits greedily under ACO's own fitness)
        assert msg["durationSum"] <= first["message"]["durationSum"] * 1.05

    def test_local_search_pool_rejects_nonsense(self, server):
        status, resp = post(
            server,
            "/api/vrp/sa",
            vrp_body(localSearch=True, localSearchPool=-4),
        )
        assert status == 400
        assert any("positive integer" in e["reason"] for e in resp["errors"])
        # validated even without localSearch (boundary policy)
        status, resp = post(
            server, "/api/vrp/sa", vrp_body(localSearchPool=0)
        )
        assert status == 400

    @needs_shard_map
    def test_local_search_pool_composes_with_islands(self, server):
        """Island solvers return their per-island champions as the
        elite pool, so pool polish composes with islands."""
        status, resp = post(
            server,
            "/api/vrp/sa",
            vrp_body(
                iterationCount=300,
                populationSize=16,
                islands=4,
                localSearch=True,
                localSearchPool=4,
                includeStats=True,
            ),
        )
        assert status == 200, resp
        msg = resp["message"]
        assert msg["stats"]["localSearch"] is True
        assert msg["stats"]["islands"] == 4
        visited = [c for v in msg["vehicles"] for c in v["tour"][1:-1]]
        assert sorted(visited) == [1, 2, 3, 4, 5, 6]


class TestExactCertificate:
    """The BF endpoints report whether the answer is PROVEN optimal —
    the certificate is the point of an exact endpoint (VERDICT r4): a
    complete enumeration / exhausted branch-and-bound tree reports
    proven=true; a deadline-cut search reports proven=false over its
    best incumbent."""

    def test_small_enumeration_reports_proven(self, server):
        status, resp = post(server, "/api/vrp/bf", vrp_body())
        assert status == 200, resp
        exact = resp["message"]["exact"]
        assert exact["proven"] is True
        assert exact["method"] == "enumeration"

    def test_tsp_bf_reports_proven(self, server):
        status, resp = post(server, "/api/tsp/bf", tsp_body())
        assert status == 200, resp
        exact = resp["message"]["exact"]
        assert exact["proven"] is True
        assert exact["method"] == "enumeration"

    def test_bnb_reports_proven_with_nodes(self, server):
        rng = np.random.default_rng(5)
        pts = rng.uniform(0, 100, size=(13, 2))
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        mem.seed_locations(
            "locs_cert",
            [{"id": i, "name": f"c{i}", "demand": 3 if i else 0} for i in range(13)],
        )
        mem.seed_durations("durs_cert", d.tolist())
        status, resp = post(
            server,
            "/api/vrp/bf",
            vrp_body(
                locationsKey="locs_cert",
                durationsKey="durs_cert",
                capacities=[12, 12, 12, 12],
                startTimes=[0, 0, 0, 0],
                timeLimit=60,
            ),
        )
        assert status == 200, resp
        exact = resp["message"]["exact"]
        assert exact["proven"] is True
        assert exact["method"] == "branch-and-bound"
        assert exact["nodes"] > 0

    def test_deadline_cut_bnb_reports_unproven(self, server):
        # 32 customers with mixed demands at timeLimit 0 ("stop ASAP",
        # i.e. the engine's 0.2 s floor): trees at this size take
        # billions of nodes (round 3 proved A-n32-k5 in 3.3B), so no
        # hardware exhausts one in the floor window — the served
        # incumbent must carry proven=false
        rng = np.random.default_rng(7)
        pts = rng.uniform(0, 100, size=(33, 2))
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        demands = [0] + [int(x) for x in rng.integers(1, 6, size=32)]
        mem.seed_locations(
            "locs_cut",
            [{"id": i, "name": f"x{i}", "demand": demands[i]} for i in range(33)],
        )
        mem.seed_durations("durs_cut", d.tolist())
        status, resp = post(
            server,
            "/api/vrp/bf",
            vrp_body(
                locationsKey="locs_cut",
                durationsKey="durs_cut",
                capacities=[20] * 6,
                startTimes=[0] * 6,
                timeLimit=0,
            ),
        )
        assert status == 200, resp
        exact = resp["message"]["exact"]
        assert exact["proven"] is False
        assert exact["method"] == "branch-and-bound"


class TestTSPSolve:
    @pytest.mark.parametrize("route", ["/api/tsp/sa", "/api/tsp/bf", "/api/tsp/ga", "/api/tsp/aco"])
    def test_solves(self, server, route):
        status, resp = post(server, route, tsp_body())
        assert status == 200, resp
        msg = resp["message"]
        want = {"duration", "vehicle", "cacheHit"}
        if route.endswith("/bf"):
            want = want | {"exact"}  # additive proof certificate (round 5)
        assert set(msg) == want
        assert msg["vehicle"][0] == 0 and msg["vehicle"][-1] == 0
        assert sorted(msg["vehicle"][1:-1]) == [1, 2, 3, 4, 5, 6]
        assert msg["duration"] > 0

    def test_subset_customers(self, server):
        status, resp = post(server, "/api/tsp/sa", tsp_body(customers=[2, 4, 6]))
        assert status == 200
        assert sorted(resp["message"]["vehicle"][1:-1]) == [2, 4, 6]

    def test_bf_dispatches_to_held_karp_beyond_enumeration(self, server):
        # 12 customers: enumeration refuses (10!-bound), so the TSP BF
        # endpoint must route to the Held-Karp subset DP and stay exact
        rng = np.random.default_rng(6)
        pts = rng.uniform(0, 100, size=(13, 2))
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        mem.seed_locations(
            "locs_hk", [{"id": i, "name": f"h{i}"} for i in range(13)]
        )
        mem.seed_durations("durs_hk", d.tolist())
        status, resp = post(
            server,
            "/api/tsp/bf",
            tsp_body(
                locationsKey="locs_hk",
                durationsKey="durs_hk",
                customers=list(range(1, 13)),
            ),
        )
        assert status == 200, resp
        msg = resp["message"]
        assert sorted(msg["vehicle"][1:-1]) == list(range(1, 13))
        from vrpms_tpu.core import make_instance
        from vrpms_tpu.solvers import solve_tsp_exact

        inst = make_instance(d, n_vehicles=1)
        want = solve_tsp_exact(inst)
        assert abs(msg["duration"] - float(want.breakdown.distance)) < 1e-2

    def test_start_node_nonzero(self, server):
        status, resp = post(
            server, "/api/tsp/sa", tsp_body(startNode=3, customers=[1, 2, 4])
        )
        assert status == 200
        v = resp["message"]["vehicle"]
        assert v[0] == 3 and v[-1] == 3
        assert sorted(v[1:-1]) == [1, 2, 4]


class TestPersistence:
    def test_unauthenticated_not_saved(self, server):
        status, _ = post(server, "/api/vrp/sa", vrp_body())
        assert status == 200
        assert mem.saved_solutions() == []

    def test_bad_token_rejected(self, server):
        status, resp = post(server, "/api/vrp/sa", vrp_body(auth="bogus"))
        assert status == 400
        assert resp["errors"][0]["what"] == "Not permitted"

    def test_vrp_save_filters_locations(self, server):
        status, resp = post(
            server, "/api/vrp/sa", vrp_body(auth="tok-alice", ignoredCustomers=[2])
        )
        assert status == 200, resp
        (saved,) = mem.saved_solutions()
        assert saved["owner"] == "alice@example.com"
        assert saved["name"] == "s1"
        assert {"durationMax", "durationSum", "locations", "vehicles"} <= set(saved)
        saved_ids = [loc["id"] for loc in saved["locations"]]
        assert 2 not in saved_ids and 0 in saved_ids

    def test_tsp_save_keeps_all_locations(self, server):
        status, _ = post(server, "/api/tsp/sa", tsp_body(auth="tok-alice"))
        assert status == 200
        (saved,) = mem.saved_solutions()
        assert {"duration", "vehicle", "locations"} <= set(saved)
        assert len(saved["locations"]) == 7


class TestTimedPaths:
    def test_time_windows_via_service(self, server):
        rng = np.random.default_rng(3)
        pts = rng.uniform(0, 50, size=(6, 2))
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        locs = [{"id": 0}] + [
            {
                "id": i,
                "demand": 1,
                "serviceTime": 2,
                "timeWindow": [0, 500],
            }
            for i in range(1, 6)
        ]
        mem.seed_locations("twl", locs)
        mem.seed_durations("twd", d.tolist())
        status, resp = post(
            server,
            "/api/vrp/sa",
            vrp_body(locationsKey="twl", durationsKey="twd", capacities=[10, 10],
                     startTimes=[0, 0]),
        )
        assert status == 200, resp
        visited = [c for v in resp["message"]["vehicles"] for c in v["tour"][1:-1]]
        assert sorted(visited) == [1, 2, 3, 4, 5]

    def test_time_sliced_matrix_via_service(self, server):
        rng = np.random.default_rng(4)
        base = rng.uniform(1, 20, size=(5, 5))
        np.fill_diagonal(base, 0)
        # matrix[i][j] == [slice0, slice1] nesting
        m3 = np.stack([base, 2 * base], axis=-1)
        mem.seed_locations("tdl", [{"id": i} for i in range(5)])
        mem.seed_durations("tdd", m3.tolist())
        status, resp = post(
            server,
            "/api/tsp/sa",
            tsp_body(locationsKey="tdl", durationsKey="tdd", customers=[1, 2, 3, 4],
                     timeSliceDuration=30),
        )
        assert status == 200, resp
        assert sorted(resp["message"]["vehicle"][1:-1]) == [1, 2, 3, 4]
        assert resp["message"]["duration"] > 0


def metric_line(text: str, prefix: str) -> float | None:
    """Value of the first exposition sample starting with `prefix`
    (label order is the instrument's declared order, so prefixes are
    deterministic); None when absent."""
    for line in text.splitlines():
        if line.startswith(prefix):
            return float(line.rsplit(" ", 1)[1])
    return None


class TestObservabilityHTTP:
    """Request-id correlation, the Content-Length fix, and /metrics
    plumbing — no solver runs, so these stay in the quick tier."""

    def test_400_envelope_echoes_request_id(self, server):
        status, resp = post(server, "/api/vrp/sa", {})
        assert status == 400
        rid = resp["requestId"]
        assert isinstance(rid, str) and len(rid) == 12
        # distinct requests carry distinct ids
        _, resp2 = post(server, "/api/vrp/sa", {})
        assert resp2["requestId"] != rid

    def test_malformed_content_length_returns_envelope(self, server):
        # int('abc') used to raise out of do_POST and kill the
        # connection; the contract's 400 envelope must come back instead
        import http.client

        host, port = server.replace("http://", "").split(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=10)
        try:
            conn.putrequest("POST", "/api/vrp/sa")
            conn.putheader("Content-Length", "abc")
            conn.endheaders()
            resp = conn.getresponse()
            body = json.loads(resp.read())
        finally:
            conn.close()
        assert resp.status == 400
        assert body["success"] is False
        assert body["errors"][0]["what"] == "Bad request"
        assert "Content-Length" in body["errors"][0]["reason"]
        assert "requestId" in body

    def test_malformed_request_line_still_gets_400(self, server):
        # parse_request send_error()s before self.path exists; the
        # observability log_request hook must tolerate that instead of
        # AttributeError-ing the connection away
        import socket

        host, port = server.replace("http://", "").split(":")
        s = socket.create_connection((host, int(port)), timeout=10)
        try:
            s.sendall(b"BOGUS\r\n\r\n")
            data = s.recv(4096)
        finally:
            s.close()
        assert b"400" in data

    def test_metrics_endpoint_exposes_request_counters(self, server):
        status, resp = post(server, "/api/vrp/sa", {})  # one 400
        assert status == 400
        status, text = get(server, "/metrics")
        assert status == 200
        # valid-looking exposition: HELP/TYPE pairs and counter samples
        assert "# TYPE vrpms_requests_total counter" in text
        errors = metric_line(
            text,
            'vrpms_requests_total{route="/api/vrp/sa",algorithm="sa",'
            'outcome="error"}',
        )
        assert errors is not None and errors >= 1
        kinds = metric_line(
            text, 'vrpms_error_envelope_total{what="Missing parameter"}'
        )
        assert kinds is not None and kinds >= 1
        # gauges answer on every scrape
        assert metric_line(text, "vrpms_uptime_seconds") > 0
        assert 'vrpms_backend_info{backend="cpu"' in text
        assert text.endswith("\n")

    def test_unmatched_routes_do_not_mint_series(self, server):
        with pytest.raises(urllib.error.HTTPError) as e:
            get(server, "/api/bogus/never-a-route")
        assert e.value.code == 404
        _, text = get(server, "/metrics")
        assert "never-a-route" not in text
        assert metric_line(
            text,
            'vrpms_requests_total{route="<unmatched>",algorithm="",'
            'outcome="error"}',
        ) >= 1


class TestObservabilitySolve:
    """The acceptance-criteria integration: a solved request and a 400,
    then /metrics must carry the split request counter, the solve-
    latency histogram, and the warm-start hit/miss counter; includeStats
    must expose the per-block convergence trace without changing the
    stats-less contract."""

    def test_metrics_after_solve_and_400(self, server):
        status, resp = post(
            server, "/api/vrp/sa", vrp_body(iterationCount=60, populationSize=8)
        )
        assert status == 200, resp
        status, _ = post(server, "/api/vrp/sa", {})
        assert status == 400
        status, text = get(server, "/metrics")
        assert status == 200
        ok = metric_line(
            text,
            'vrpms_requests_total{route="/api/vrp/sa",algorithm="sa",'
            'outcome="ok"}',
        )
        err = metric_line(
            text,
            'vrpms_requests_total{route="/api/vrp/sa",algorithm="sa",'
            'outcome="error"}',
        )
        assert ok >= 1 and err >= 1
        assert "# TYPE vrpms_solve_seconds histogram" in text
        assert metric_line(
            text, 'vrpms_solve_seconds_count{problem="vrp",algorithm="sa"}'
        ) >= 1
        assert metric_line(
            text, 'vrpms_solve_seconds_bucket{problem="vrp",algorithm="sa",'
        ) is not None
        assert metric_line(text, "vrpms_solve_evals_count") >= 1
        assert metric_line(text, "vrpms_request_body_bytes_count") >= 1

    def test_warmstart_miss_then_hit_counted(self, server):
        body = vrp_body(
            solutionName="obs-warm", iterationCount=60, populationSize=8,
            warmStart=True, auth="tok-alice",
        )
        status, _ = post(server, "/api/vrp/sa", body)  # no checkpoint: miss
        assert status == 200
        status, resp = post(server, "/api/vrp/sa", body)  # checkpoint: hit
        assert status == 200, resp
        _, text = get(server, "/metrics")
        assert metric_line(
            text, 'vrpms_warmstart_lookups_total{outcome="miss"}'
        ) >= 1
        assert metric_line(
            text, 'vrpms_warmstart_lookups_total{outcome="hit"}'
        ) >= 1

    def test_include_stats_exposes_trace(self, server):
        status, resp = post(
            server,
            "/api/vrp/sa",
            vrp_body(iterationCount=200, populationSize=8, includeStats=True),
        )
        assert status == 200, resp
        stats = resp["message"]["stats"]
        trace = stats["trace"]
        assert isinstance(trace, list) and len(trace) >= 1
        for entry in trace:
            assert set(entry) == {"wallMs", "bestCost", "evals"}
            assert entry["wallMs"] >= 0 and entry["evals"] > 0
        evals = [e["evals"] for e in trace]
        assert evals == sorted(evals)
        assert trace[-1]["evals"] == stats["evals"]
        bests = [e["bestCost"] for e in trace]
        assert bests == sorted(bests, reverse=True)  # best never worsens
        conv = stats["convergence"]
        assert conv["blocks"] == len(trace)
        assert conv["firstBlockMs"] > 0

    def test_trace_covers_deadline_blocks(self, server):
        status, resp = post(
            server,
            "/api/vrp/sa",
            vrp_body(iterationCount=1500, populationSize=8,
                     includeStats=True, timeLimit=60),
        )
        assert status == 200, resp
        trace = resp["message"]["stats"]["trace"]
        # a deadline-blocked anneal syncs per block: several entries
        assert len(trace) >= 2

    def test_stats_absent_is_byte_identical_contract(self, server):
        body = vrp_body(iterationCount=100, populationSize=8)
        status, plain = post(server, "/api/vrp/sa", body)
        assert status == 200, plain
        status, with_stats = post(
            server, "/api/vrp/sa", dict(body, includeStats=True)
        )
        assert status == 200, with_stats
        assert set(plain["message"]) == {
            "durationMax", "durationSum", "vehicles", "cacheHit"
        }
        stripped = dict(with_stats["message"])
        del stripped["stats"]
        # identical solve modulo the additive stats key (same seed, same
        # program — the telemetry must not perturb the search)
        assert stripped == plain["message"]


class TestCORS:
    def test_vrp_ga_preflight(self, server):
        req = urllib.request.Request(server + "/api/vrp/ga", method="OPTIONS")
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 200
            assert resp.headers["Access-Control-Allow-Origin"] == "*"

    def test_other_routes_no_preflight(self, server):
        req = urllib.request.Request(server + "/api/vrp/sa", method="OPTIONS")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req)
        assert e.value.code == 501

    def test_vrp_ga_responses_carry_static_cors_headers(self, server):
        # The reference's edge config attaches CORS headers to every
        # /api/vrp/ga RESPONSE (vercel.json:4-11) — a browser's actual
        # POST (not just its preflight) must see them.
        status, resp, headers = post_h(
            server, "/api/vrp/ga", vrp_body(
                multiThreaded=False, randomPermutationCount=32,
                iterationCount=20, populationSize=16,
            )
        )
        assert status == 200, resp
        assert headers["Access-Control-Allow-Origin"] == "*"
        assert headers["Access-Control-Allow-Credentials"] == "true"
        assert "POST" in headers["Access-Control-Allow-Methods"]
        assert "Content-Type" in headers["Access-Control-Allow-Headers"]
        # error envelopes are responses too
        status, _, headers = post_h(server, "/api/vrp/ga", {})
        assert status == 400
        assert headers["Access-Control-Allow-Origin"] == "*"
        # and the GET banner
        req = urllib.request.Request(server + "/api/vrp/ga")
        with urllib.request.urlopen(req) as resp:
            assert resp.headers["Access-Control-Allow-Origin"] == "*"

    def test_other_routes_no_static_cors_headers(self, server):
        # reference parity: only /api/vrp/ga has the edge headers
        status, _, headers = post_h(server, "/api/vrp/sa", {})
        assert status == 400
        assert headers.get("Access-Control-Allow-Origin") is None
