"""Island-model tests on the virtual 8-device CPU mesh (SURVEY.md §4 item 5)."""

import numpy as np
import jax
import pytest

# the island mesh is built on jax.shard_map, which older jax (e.g. the
# 0.4.x line some containers pin) does not expose — there the islands
# suite is PRE-BROKEN by the environment, not by the code under test:
# report skips, not failures
pytestmark = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="jax.shard_map unavailable (old jax); islands need it",
)

from vrpms_tpu.core.encoding import is_valid_giant
from vrpms_tpu.mesh import make_mesh, solve_sa_islands, solve_ga_islands, IslandParams
from vrpms_tpu.solvers import solve_vrp_bf
from vrpms_tpu.solvers.ga import GAParams
from vrpms_tpu.solvers.sa import SAParams
from tests.test_sa import euclidean_cvrp


class TestIslandMesh:
    def test_mesh_has_8_devices(self):
        mesh = make_mesh()
        assert mesh.shape["islands"] == 8

    def test_sa_islands_near_optimal(self, rng):
        inst = euclidean_cvrp(rng, n=8, v=3, q=8)
        opt = float(solve_vrp_bf(inst).cost)
        res = solve_sa_islands(
            inst,
            key=0,
            params=SAParams(n_chains=64, n_iters=2000),
            island_params=IslandParams(migrate_every=200, n_migrants=2),
        )
        assert is_valid_giant(res.giant, 7, 3)
        assert float(res.cost) <= opt * 1.05 + 1e-3

    def test_ga_islands_near_optimal(self, rng):
        inst = euclidean_cvrp(rng, n=8, v=3, q=8)
        opt = float(solve_vrp_bf(inst).cost)
        res = solve_ga_islands(
            inst,
            key=0,
            params=GAParams(population=128, generations=200, elites=4),
            island_params=IslandParams(migrate_every=50, n_migrants=2),
        )
        assert is_valid_giant(res.giant, 7, 3)
        assert float(res.cost) <= opt * 1.05 + 1e-3

    def test_subset_mesh(self, rng):
        inst = euclidean_cvrp(rng, n=8, v=2, q=15)
        mesh = make_mesh(n_devices=4)
        res = solve_sa_islands(
            inst,
            key=1,
            mesh=mesh,
            params=SAParams(n_chains=32, n_iters=500),
            island_params=IslandParams(migrate_every=100, n_migrants=1),
        )
        assert is_valid_giant(res.giant, 7, 2)

    def test_sa_islands_deadline_matches_unbounded_when_never_hit(self, rng):
        """The chunked deadline program must reproduce the single-shot
        one exactly (same fold-in indices, same migration points)."""
        inst = euclidean_cvrp(rng, n=10, v=2, q=20)
        kw = dict(
            key=3,
            params=SAParams(n_chains=32, n_iters=450),
            island_params=IslandParams(migrate_every=100, n_migrants=2),
        )
        free = solve_sa_islands(inst, **kw)
        timed = solve_sa_islands(inst, deadline_s=3600.0, **kw)
        assert float(free.cost) == float(timed.cost)
        assert np.array_equal(np.asarray(free.giant), np.asarray(timed.giant))
        assert int(free.evals) == int(timed.evals)

    def test_ga_islands_deadline_matches_unbounded_when_never_hit(self, rng):
        inst = euclidean_cvrp(rng, n=9, v=2, q=15)
        kw = dict(
            key=4,
            params=GAParams(population=32, generations=110, elites=2),
            island_params=IslandParams(migrate_every=50, n_migrants=2),
        )
        free = solve_ga_islands(inst, **kw)
        timed = solve_ga_islands(inst, deadline_s=3600.0, **kw)
        assert float(free.cost) == float(timed.cost)
        assert np.array_equal(np.asarray(free.giant), np.asarray(timed.giant))

    def test_islands_deadline_truncates(self, rng):
        inst = euclidean_cvrp(rng, n=10, v=2, q=20)
        res = solve_sa_islands(
            inst,
            key=5,
            params=SAParams(n_chains=32, n_iters=100_000),
            island_params=IslandParams(migrate_every=100, n_migrants=2),
            deadline_s=1e-6,
        )
        assert is_valid_giant(res.giant, 9, 2)
        assert 0 < int(res.evals) < 32 * 100_000

    def test_islands_deadline_bounds_migrationless_tail(self, rng):
        """migrateEvery > n_iters leaves everything in the tail; the
        deadline must still truncate it (chunked, not one shot)."""
        inst = euclidean_cvrp(rng, n=10, v=2, q=20)
        res = solve_sa_islands(
            inst,
            key=6,
            params=SAParams(n_chains=32, n_iters=100_000),
            island_params=IslandParams(migrate_every=10_000_000, n_migrants=2),
            deadline_s=1e-6,
        )
        assert is_valid_giant(res.giant, 9, 2)
        assert 0 < int(res.evals) < 32 * 100_000

    def test_ga_islands_pool_returns_champion_first(self, rng):
        inst = euclidean_cvrp(rng, n=10, v=2, q=20)
        kw = dict(
            key=8,
            params=GAParams(population=32, generations=40, elites=2),
            island_params=IslandParams(migrate_every=20, n_migrants=2),
        )
        for deadline in (None, 3600.0):
            res = solve_ga_islands(inst, deadline_s=deadline, pool=3, **kw)
            assert res.pool is not None and res.pool.shape[0] == 3
            assert np.array_equal(np.asarray(res.pool[0]), np.asarray(res.giant))
            for g in np.asarray(res.pool):
                assert is_valid_giant(g, 9, 2)

    def test_ils_islands_valid_and_competitive(self, rng):
        from vrpms_tpu.mesh import solve_ils_islands
        from vrpms_tpu.solvers import ILSParams

        inst = euclidean_cvrp(rng, n=16, v=3, q=10)
        plain = solve_sa_islands(
            inst,
            key=2,
            params=SAParams(n_chains=32, n_iters=1200),
            island_params=IslandParams(migrate_every=100, n_migrants=2),
        )
        ils = solve_ils_islands(
            inst,
            key=2,
            params=ILSParams.from_budget(
                3, SAParams(n_chains=32, n_iters=0), 1200, pool=4
            ),
            island_params=IslandParams(migrate_every=100, n_migrants=2),
        )
        assert is_valid_giant(ils.giant, 15, 3)
        # champion polish alone guarantees near-parity
        assert float(ils.cost) <= float(plain.cost) * 1.02 + 1e-3

    def test_ils_islands_deadline_truncates(self, rng):
        from vrpms_tpu.mesh import solve_ils_islands
        from vrpms_tpu.solvers import ILSParams

        inst = euclidean_cvrp(rng, n=10, v=2, q=20)
        res = solve_ils_islands(
            inst,
            key=7,
            params=ILSParams.from_budget(
                50, SAParams(n_chains=16, n_iters=0), 1_000_000, pool=4
            ),
            island_params=IslandParams(migrate_every=100, n_migrants=1),
            deadline_s=1e-6,
        )
        assert is_valid_giant(res.giant, 9, 2)
        assert 0 < int(res.evals) < 16 * 1_000_000

    def test_migration_spreads_elites(self, rng):
        # With migration every step and a tiny per-island batch, all
        # islands should converge on comparable costs; mainly this
        # exercises ppermute correctness (no crash, valid output).
        inst = euclidean_cvrp(rng, n=10, v=2, q=20)
        res = solve_sa_islands(
            inst,
            key=2,
            params=SAParams(n_chains=16, n_iters=200),
            island_params=IslandParams(migrate_every=10, n_migrants=1),
        )
        assert is_valid_giant(res.giant, 9, 2)
