"""Island-model tests on the virtual 8-device CPU mesh (SURVEY.md §4 item 5)."""

import numpy as np
import jax

from vrpms_tpu.core.encoding import is_valid_giant
from vrpms_tpu.mesh import make_mesh, solve_sa_islands, solve_ga_islands, IslandParams
from vrpms_tpu.solvers import solve_vrp_bf
from vrpms_tpu.solvers.ga import GAParams
from vrpms_tpu.solvers.sa import SAParams
from tests.test_sa import euclidean_cvrp


class TestIslandMesh:
    def test_mesh_has_8_devices(self):
        mesh = make_mesh()
        assert mesh.shape["islands"] == 8

    def test_sa_islands_near_optimal(self, rng):
        inst = euclidean_cvrp(rng, n=8, v=3, q=8)
        opt = float(solve_vrp_bf(inst).cost)
        res = solve_sa_islands(
            inst,
            key=0,
            params=SAParams(n_chains=64, n_iters=2000),
            island_params=IslandParams(migrate_every=200, n_migrants=2),
        )
        assert is_valid_giant(res.giant, 7, 3)
        assert float(res.cost) <= opt * 1.05 + 1e-3

    def test_ga_islands_near_optimal(self, rng):
        inst = euclidean_cvrp(rng, n=8, v=3, q=8)
        opt = float(solve_vrp_bf(inst).cost)
        res = solve_ga_islands(
            inst,
            key=0,
            params=GAParams(population=128, generations=200, elites=4),
            island_params=IslandParams(migrate_every=50, n_migrants=2),
        )
        assert is_valid_giant(res.giant, 7, 3)
        assert float(res.cost) <= opt * 1.05 + 1e-3

    def test_subset_mesh(self, rng):
        inst = euclidean_cvrp(rng, n=8, v=2, q=15)
        mesh = make_mesh(n_devices=4)
        res = solve_sa_islands(
            inst,
            key=1,
            mesh=mesh,
            params=SAParams(n_chains=32, n_iters=500),
            island_params=IslandParams(migrate_every=100, n_migrants=1),
        )
        assert is_valid_giant(res.giant, 7, 2)

    def test_migration_spreads_elites(self, rng):
        # With migration every step and a tiny per-island batch, all
        # islands should converge on comparable costs; mainly this
        # exercises ppermute correctness (no crash, valid output).
        inst = euclidean_cvrp(rng, n=10, v=2, q=20)
        res = solve_sa_islands(
            inst,
            key=2,
            params=SAParams(n_chains=16, n_iters=200),
            island_params=IslandParams(migrate_every=10, n_migrants=1),
        )
        assert is_valid_giant(res.giant, 9, 2)
