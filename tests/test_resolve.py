"""Dynamic re-solve (ISSUE 8): warm-start continuation, instance
deltas, cancel-and-resolve.

Unit layers (quick): the shared strip/insert repair always yields a
valid permutation (and a structurally valid giant after the greedy
split), degenerate deltas behave (everything dropped, empty routes),
request-delta validation rejects duplicate adds / unknown ids with
Data-error envelope entries, and the SA continuation schedule stays
inside [t_final, warm-start t0].

End-to-end layers (slow via conftest patterns; tier1.yml runs the file
in full): delta requests solve exactly the post-delta customer set,
`warmStart` objects seed from an inline tour and from a prior jobId
with the cache OFF (seed retrieval must not silently depend on
VRPMS_CACHE), and `POST /api/jobs/{id}/resolve` cancels a running job
and hands its incumbent to the successor — whose first published
incumbent never costs more than the predecessor's final one.
"""

import os
import time

import numpy as np
import pytest

from vrpms_tpu.core import make_instance
from vrpms_tpu.core import delta as delta_mod
from vrpms_tpu.core.encoding import is_valid_giant
from vrpms_tpu.core.split import greedy_split_giant
from tests.test_progress import (  # noqa: F401  (fixtures)
    job_body,
    poll_done,
    request,
    seeded,
    server,
)


@pytest.fixture(autouse=True)
def cache_env():
    """Restore the cache knob after each test (read per call)."""
    saved = os.environ.get("VRPMS_CACHE")
    yield
    if saved is None:
        os.environ.pop("VRPMS_CACHE", None)
    else:
        os.environ["VRPMS_CACHE"] = saved


def served_customers(msg):
    return sorted(c for v in msg["vehicles"] for c in v["tour"][1:-1])


# ---------------------------------------------------------------------------
# unit: the shared repair
# ---------------------------------------------------------------------------


class TestRepair:
    def _durations(self, rng, n):
        pts = rng.uniform(0, 100, size=(n, 2))
        return np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)

    def test_repair_always_yields_valid_permutation(self, rng):
        # randomized: arbitrary prior routes (dropped ids, new ids,
        # duplicates across routes) repair to a permutation of the
        # CURRENT active positions 1..n-1, every customer exactly once
        for trial in range(30):
            n = int(rng.integers(3, 12))
            active_ids = [0] + sorted(
                rng.choice(np.arange(1, 50), size=n - 1, replace=False)
                .tolist()
            )
            d = self._durations(rng, n)
            # prior solution over a random overlapping id set
            prior_ids = [
                i for i in active_ids[1:] if rng.random() < 0.6
            ] + rng.choice(np.arange(50, 70), size=2, replace=False).tolist()
            prior_ids = [int(x) for x in rng.permutation(prior_ids)]
            cut = len(prior_ids) // 2
            routes = [prior_ids[:cut], prior_ids[cut:]]
            order = delta_mod.repair_order(routes, active_ids, d)
            survivors = {
                i for i, cid in enumerate(active_ids)
                if i > 0 and cid in set(prior_ids)
            }
            if not survivors:
                assert order is None
                continue
            assert sorted(order) == list(range(1, n))

    def test_survivors_keep_relative_order(self):
        active = [0, 10, 20, 30, 40]
        d = np.ones((5, 5))
        order = delta_mod.repair_order([[40, 20, 10]], active, d)
        # 30 is new (greedy-inserted somewhere); survivors stay 4,2,1
        assert [p for p in order if p in (4, 2, 1)] == [4, 2, 1]
        assert sorted(order) == [1, 2, 3, 4]

    def test_nothing_survives_declines_to_seed(self):
        d = np.ones((4, 4))
        assert delta_mod.repair_order([[99], []], [0, 1, 2, 3], d) is None
        assert delta_mod.repair_perm([], [0, 1, 2, 3], d) is None

    def test_empty_routes_in_prior_solution_are_fine(self):
        # a cancelled/partial predecessor can hold empty routes
        d = np.ones((4, 4))
        order = delta_mod.repair_order([[], [3, 1], []], [0, 1, 2, 3], d)
        assert sorted(order) == [1, 2, 3]

    def test_repaired_giant_is_structurally_valid(self, rng):
        # through the greedy split, the repaired permutation decodes to
        # a giant with the encoding's exact separator count
        n, v = 7, 3
        d = self._durations(rng, n)
        inst = make_instance(
            d, demands=[0] + [1] * (n - 1), capacities=[n] * v
        )
        active_ids = list(range(n))
        routes = [[3, 1], [5, 2]]  # drops 4, 6; nothing new beyond them
        perm = delta_mod.repair_perm(routes, active_ids, d)
        giant = greedy_split_giant(perm, inst)
        assert is_valid_giant(giant, n - 1, v)

    def test_greedy_insert_picks_cheapest_position(self):
        # a 1-D line: inserting 2 between 1 and 3 is cheapest
        pts = np.asarray([0.0, 1.0, 2.0, 3.0])
        d = np.abs(pts[:, None] - pts[None, :])
        order = delta_mod.repair_order([[1, 3]], [0, 1, 2, 3], d)
        assert order == [1, 2, 3]


# ---------------------------------------------------------------------------
# unit: request-delta validation + application
# ---------------------------------------------------------------------------


def _vrp_params(ignored=(), completed=()):
    return {
        "ignored_customers": list(ignored),
        "completed_customers": list(completed),
    }


def _locs(n=5):
    return [{"id": i, "demand": 2 if i else 0} for i in range(n)]


class TestApplyDelta:
    def test_vrp_drop_moves_id_into_ignored(self):
        params, errors = _vrp_params(), []
        out = delta_mod.apply_request_delta(
            "vrp", params, _locs(), {"drop": [2]}, errors
        )
        assert not errors and out is not None
        assert params["ignored_customers"] == [2]

    def test_vrp_add_reactivates_excluded(self):
        params, errors = _vrp_params(ignored=[2], completed=[3]), []
        out = delta_mod.apply_request_delta(
            "vrp", params, _locs(), {"add": [2, 3]}, errors
        )
        assert not errors and out is not None
        assert params["ignored_customers"] == []
        assert params["completed_customers"] == []

    def test_duplicate_add_rejected(self):
        params, errors = _vrp_params(), []
        out = delta_mod.apply_request_delta(
            "vrp", params, _locs(), {"add": [1]}, errors
        )
        assert out is None
        assert any("duplicate add" in e["reason"] for e in errors)

    def test_drop_of_inactive_rejected(self):
        params, errors = _vrp_params(ignored=[2]), []
        out = delta_mod.apply_request_delta(
            "vrp", params, _locs(), {"drop": [2]}, errors
        )
        assert out is None and any(
            "not active" in e["reason"] for e in errors
        )

    def test_unknown_id_and_unknown_key_rejected(self):
        params, errors = _vrp_params(), []
        assert delta_mod.apply_request_delta(
            "vrp", params, _locs(), {"drop": [99]}, errors
        ) is None
        errors2: list = []
        assert delta_mod.apply_request_delta(
            "vrp", params, _locs(), {"remove": [1]}, errors2
        ) is None
        assert any("unknown delta key" in e["reason"] for e in errors2)

    def test_depot_protected(self):
        params, errors = _vrp_params(), []
        assert delta_mod.apply_request_delta(
            "vrp", params, _locs(), {"drop": [0]}, errors
        ) is None

    def test_demand_and_window_changes_copy_locations(self):
        locs = _locs()
        params, errors = _vrp_params(), []
        out = delta_mod.apply_request_delta(
            "vrp", params, locs,
            {"demands": {"2": 5}, "timeWindows": {"3": [10, 20]}}, errors,
        )
        assert not errors
        assert out[2]["demand"] == 5.0 and out[3]["timeWindow"] == [10, 20]
        # the stored dataset rows were never mutated
        assert locs[2]["demand"] == 2 and "timeWindow" not in locs[3]

    def test_window_null_clears_and_inverted_rejected(self):
        locs = _locs()
        locs[2]["timeWindow"] = [0, 9]
        params, errors = _vrp_params(), []
        out = delta_mod.apply_request_delta(
            "vrp", params, locs, {"timeWindows": {"2": None}}, errors
        )
        assert not errors and "timeWindow" not in out[2]
        errors2: list = []
        assert delta_mod.apply_request_delta(
            "vrp", params, locs, {"timeWindows": {"2": [9, 1]}}, errors2
        ) is None

    def test_tsp_add_drop_edit_customer_list(self):
        params, errors = {"customers": [1, 2, 3], "start_node": 0}, []
        out = delta_mod.apply_request_delta(
            "tsp", params, _locs(), {"drop": [2], "add": [4]}, errors
        )
        assert not errors and out is not None
        assert params["customers"] == [1, 3, 4]

    def test_tsp_demands_rejected(self):
        params, errors = {"customers": [1, 2], "start_node": 0}, []
        assert delta_mod.apply_request_delta(
            "tsp", params, _locs(), {"demands": {"1": 3}}, errors
        ) is None
        assert any("VRP" in e["reason"] for e in errors)


# ---------------------------------------------------------------------------
# unit: the SA continuation schedule
# ---------------------------------------------------------------------------


class TestContinuation:
    def test_t0_clamped_between_final_and_warm(self, rng):
        import jax.numpy as jnp

        from vrpms_tpu.solvers.sa import (
            SAParams,
            _temps_from_scale,
            continuation_params,
        )
        from vrpms_tpu.solvers.sa import _mean_fn

        n = 8
        pts = rng.uniform(0, 100, size=(n, 2))
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        inst = make_instance(
            d, demands=[0] + [1] * (n - 1), capacities=[n, n]
        )
        perm = jnp.arange(1, n, dtype=jnp.int32)
        giant = greedy_split_giant(perm, inst)
        p = continuation_params(inst, SAParams(), giant)
        scale = float(_mean_fn()(inst))
        t_warm, t1 = _temps_from_scale(scale, SAParams())
        assert p.t_initial is not None
        assert t1 <= p.t_initial <= t_warm
        # an explicit t_initial always wins untouched
        explicit = SAParams(t_initial=123.0)
        assert continuation_params(inst, explicit, giant).t_initial == 123.0


# ---------------------------------------------------------------------------
# HTTP: envelopes (no solving — quick)
# ---------------------------------------------------------------------------


class TestEnvelopes:
    def test_duplicate_add_400(self, server):
        status, r = request(
            server, "POST", "/api/vrp/sa", job_body(delta={"add": [1]})
        )
        assert status == 400
        assert any("duplicate add" in e["reason"] for e in r["errors"])

    def test_unknown_delta_key_400(self, server):
        status, r = request(
            server, "POST", "/api/vrp/sa", job_body(delta={"append": [1]})
        )
        assert status == 400

    def test_async_submit_validates_delta_too(self, server):
        status, r = request(
            server, "POST", "/api/jobs", job_body(delta={"drop": [99]})
        )
        assert status == 400
        assert any("not in the locations" in e["reason"] for e in r["errors"])

    def test_bad_warmstart_spec_400(self, server):
        status, r = request(
            server, "POST", "/api/vrp/sa",
            job_body(warmStart={"sessionId": "x"}),
        )
        assert status == 400
        assert any("warmStart" in e["reason"] for e in r["errors"])
        status, r = request(
            server, "POST", "/api/vrp/sa", job_body(warmStart={})
        )
        assert status == 400

    def test_resolve_unknown_job_404(self, server):
        status, r = request(
            server, "POST", "/api/jobs/nope/resolve", job_body()
        )
        assert status == 404

    def test_resolve_malformed_body_400_without_record_read(self, server):
        status, r = request(
            server, "POST", "/api/jobs/nope/resolve", {"problem": "vrp"}
        )
        assert status == 400

    def test_all_customers_dropped_is_trivial(self, server):
        status, r = request(
            server, "POST", "/api/vrp/sa",
            job_body(delta={"drop": [1, 2, 3, 4, 5, 6]}),
        )
        assert status == 200, r
        assert r["message"]["durationMax"] == 0
        assert r["message"]["vehicles"] == []


# ---------------------------------------------------------------------------
# HTTP: delta solves (slow)
# ---------------------------------------------------------------------------


class TestDeltaHTTP:
    def test_solve_covers_exactly_the_post_delta_set(self, server):
        body = job_body(
            ignoredCustomers=[6], iterationCount=300, populationSize=8
        )
        status, r = request(
            server, "POST", "/api/vrp/sa",
            dict(body, delta={"drop": [2], "add": [6]}),
        )
        assert status == 200, r
        assert served_customers(r["message"]) == [1, 3, 4, 5, 6]

    def test_demand_change_fails_capacity_differently(self, server):
        # raising one demand past every capacity must change the load
        # the response reports (the instance really was rebuilt)
        body = job_body(iterationCount=200, populationSize=8)
        status, r = request(
            server, "POST", "/api/vrp/sa",
            dict(body, delta={"demands": {"1": 9}}),
        )
        assert status == 200, r
        loads = {
            c: v["load"]
            for v in r["message"]["vehicles"]
            for c in v["tour"][1:-1]
        }
        assert loads  # solved normally
        v1 = next(
            v for v in r["message"]["vehicles"] if 1 in v["tour"][1:-1]
        )
        assert v1["load"] >= 9


# ---------------------------------------------------------------------------
# HTTP: explicit warm-start specs (slow)
# ---------------------------------------------------------------------------


class TestWarmStartSpec:
    def test_inline_tour_seeds_and_continues(self, server):
        body = job_body(
            iterationCount=300, populationSize=8, includeStats=True
        )
        status, r = request(server, "POST", "/api/vrp/sa", body)
        assert status == 200, r
        routes = [v["tour"][1:-1] for v in r["message"]["vehicles"]]
        status, r2 = request(
            server, "POST", "/api/vrp/sa",
            dict(body, warmStart={"tour": routes}),
        )
        assert status == 200, r2
        stats = r2["message"]["stats"]
        assert stats["warmStart"] is True
        assert stats["resolve"] == {
            "seedSource": "tour", "seeded": True, "continuation": True,
        }
        # never worse than the cold solve it was seeded from
        assert (
            r2["message"]["durationSum"]
            <= r["message"]["durationSum"] + 1e-6
        )

    def test_jobid_seed_works_with_cache_off(self, server):
        os.environ["VRPMS_CACHE"] = "off"
        status, resp = request(
            server, "POST", "/api/jobs",
            job_body(iterationCount=300, populationSize=8),
        )
        assert status == 202, resp
        record = poll_done(server, resp["jobId"])
        assert record["status"] == "done"
        status, r = request(
            server, "POST", "/api/vrp/sa",
            job_body(
                iterationCount=300, populationSize=8, includeStats=True,
                warmStart={"jobId": resp["jobId"]},
            ),
        )
        assert status == 200, r
        stats = r["message"]["stats"]
        assert stats["resolve"]["seedSource"] == "job"
        assert stats["resolve"]["seeded"] is True
        # cache off: no cacheHit key, exactly like the pre-cache contract
        assert "cacheHit" not in r["message"]

    def test_unknown_jobid_degrades_to_cold_solve(self, server):
        status, r = request(
            server, "POST", "/api/vrp/sa",
            job_body(
                iterationCount=200, populationSize=8, includeStats=True,
                warmStart={"jobId": "no-such-job"},
            ),
        )
        assert status == 200, r
        stats = r["message"]["stats"]
        assert stats["resolve"] == {
            "seedSource": "miss", "seeded": False, "continuation": False,
            "jobId": "no-such-job",
        }
        assert stats["warmStart"] is False

    def test_tour_with_delta_covers_new_set_and_seeds(self, server):
        body = job_body(
            ignoredCustomers=[6], iterationCount=300, populationSize=8
        )
        status, r = request(server, "POST", "/api/vrp/sa", body)
        assert status == 200, r
        routes = [v["tour"][1:-1] for v in r["message"]["vehicles"]]
        status, r2 = request(
            server, "POST", "/api/vrp/sa",
            dict(
                body, includeStats=True,
                warmStart={"tour": routes},
                delta={"drop": [1], "add": [6]},
            ),
        )
        assert status == 200, r2
        assert served_customers(r2["message"]) == [2, 3, 4, 5, 6]
        assert r2["message"]["stats"]["resolve"]["seeded"] is True


# ---------------------------------------------------------------------------
# HTTP: cancel-and-resolve (slow)
# ---------------------------------------------------------------------------


class TestResolveEndpoint:
    def test_cancel_and_resolve_continues_from_incumbent(self, server):
        status, resp = request(
            server, "POST", "/api/jobs",
            job_body(iterationCount=50_000_000, timeLimit=120.0, seed=3),
        )
        assert status == 202, resp
        pred_id = resp["jobId"]
        # wait for a published incumbent so there is something to seize
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            _, r = request(server, "GET", f"/api/jobs/{pred_id}")
            if r["job"].get("incumbent") or r["job"]["status"] in (
                "done", "failed",
            ):
                break
            time.sleep(0.05)
        status, r = request(
            server, "POST", f"/api/jobs/{pred_id}/resolve",
            job_body(iterationCount=2000, seed=4),
        )
        assert status == 202, r
        assert r["resolvedFrom"] == pred_id
        succ = poll_done(server, r["jobId"])
        pred = poll_done(server, pred_id)
        assert pred["status"] == "done"
        assert pred["message"].get("cancelled") is True
        assert succ["status"] == "done"
        assert succ["resolvedFrom"] == pred_id
        # acceptance: the successor's FIRST published incumbent costs no
        # more than the predecessor's final one (same customer set —
        # clone 0 of the seed is exactly the predecessor's incumbent)
        pred_final = pred["incumbent"]["bestCost"]
        succ_first = succ["progress"]["improvements"][0]["bestCost"]
        assert succ_first <= pred_final + 1e-6

    def test_bad_body_never_cancels_the_predecessor(self, server):
        # the full parse ladder (delta validation included) runs BEFORE
        # the predecessor is touched: a malformed successor must not
        # cost the running job its budget
        status, resp = request(
            server, "POST", "/api/jobs",
            job_body(iterationCount=50_000_000, timeLimit=60.0, seed=5),
        )
        assert status == 202, resp
        pred_id = resp["jobId"]
        status, r = request(
            server, "POST", f"/api/jobs/{pred_id}/resolve",
            job_body(delta={"drop": [99]}),
        )
        assert status == 400
        _, rr = request(server, "GET", f"/api/jobs/{pred_id}")
        assert rr["job"]["status"] in ("queued", "running")
        assert rr["job"].get("message", {}).get("cancelled") is not True
        # clean up so the suite does not wait out the 60 s budget
        request(server, "DELETE", f"/api/jobs/{pred_id}")
        poll_done(server, pred_id)

    def test_resolve_finished_job_seeds_without_cancel(self, server):
        status, resp = request(
            server, "POST", "/api/jobs",
            job_body(iterationCount=300, populationSize=8),
        )
        assert status == 202, resp
        poll_done(server, resp["jobId"])
        status, r = request(
            server, "POST", f"/api/jobs/{resp['jobId']}/resolve",
            job_body(
                iterationCount=300, populationSize=8,
                delta={"drop": [4]},
            ),
        )
        assert status == 202, r
        succ = poll_done(server, r["jobId"])
        assert succ["status"] == "done"
        assert served_customers(succ["message"]) == [1, 2, 3, 5, 6]
