"""Shape-tier canonicalization: ladder config, padding mechanics, the
scheduler bucket coarsening, and the compile-count regression guard."""

import numpy as np
import pytest

import jax.numpy as jnp

from vrpms_tpu.core import tiers
from vrpms_tpu.core.instance import BIG, make_instance
from vrpms_tpu.io.synth import synth_cvrp

LADDER = tiers.TierLadder(
    tiers.DEFAULT_N_TIERS, tiers.DEFAULT_V_TIERS, tiers.DEFAULT_T_TIERS
)


class TestLadderConfig:
    def test_default_spec(self):
        lad = tiers.parse_tiers("")
        assert lad.n == tiers.DEFAULT_N_TIERS
        assert lad.v == tiers.DEFAULT_V_TIERS
        assert lad.t == tiers.DEFAULT_T_TIERS

    def test_off(self):
        assert tiers.parse_tiers("off") is None
        assert tiers.parse_tiers("none") is None

    def test_custom_axes(self):
        lad = tiers.parse_tiers("n=8,32,16;v=")
        assert lad.n == (8, 16, 32)  # sorted
        assert lad.v == ()  # explicitly disabled axis
        assert lad.t == tiers.DEFAULT_T_TIERS  # untouched axis

    def test_unknown_axis_rejected(self):
        with pytest.raises(ValueError):
            tiers.parse_tiers("q=1,2")

    def test_env_ladder(self, monkeypatch):
        monkeypatch.setenv("VRPMS_TIERS", "off")
        assert tiers.ladder() is None
        monkeypatch.setenv("VRPMS_TIERS", "n=4,8")
        assert tiers.ladder().n == (4, 8)

    def test_tier_up(self):
        assert tiers.tier_up(13, (8, 16, 24)) == 16
        assert tiers.tier_up(16, (8, 16, 24)) == 16
        assert tiers.tier_up(99, (8, 16, 24)) == 99  # beyond the ladder

    def test_tier_up_multiple(self):
        assert tiers.tier_up_multiple(8, (1, 8, 24, 48)) == 8
        assert tiers.tier_up_multiple(12, (1, 8, 24, 48)) == 24
        assert tiers.tier_up_multiple(7, (1, 8, 24, 48)) == 7  # no multiple


class TestPadInstance:
    def test_shapes_and_counts(self):
        inst = synth_cvrp(13, 3, seed=0)
        p = tiers.pad_instance(inst, LADDER)
        assert p.durations.shape == (1, 16, 16)
        assert p.n_vehicles == 4
        assert int(p.n_real) == 13 and int(p.v_real) == 3
        assert p.padded and not inst.padded
        assert int(p.move_limit) == 13 + 3

    def test_depot_alias_values(self):
        inst = synth_cvrp(11, 2, seed=1)
        p = tiers.pad_instance(inst, LADDER)
        d = np.asarray(p.durations[0])
        # phantom rows/cols copy the depot's; phantom-phantom legs free
        assert np.array_equal(d[13, :11], d[0, :11])
        assert np.array_equal(d[:11, 14], d[:11, 0])
        assert d[13, 14] == 0.0 and d[0, 13] == 0.0
        assert np.all(np.asarray(p.demands)[11:] == 0.0)
        assert np.all(np.asarray(p.due)[11:] == BIG)
        assert np.all(np.asarray(p.capacities)[2:] == 0.0)

    def test_metadata_preserved(self):
        inst = synth_cvrp(10, 2, seed=2)
        het = make_instance(
            np.asarray(inst.durations[0]),
            demands=np.asarray(inst.demands),
            capacities=[20.0, 30.0],
        )
        p = tiers.pad_instance(het, LADDER)
        # the REAL fleet's het flag survives (phantom zero capacities
        # must not flip solver paths)
        assert p.het_fleet == het.het_fleet
        assert p.has_tw == het.has_tw

    def test_t_axis_tiles_exactly(self):
        rng = np.random.default_rng(3)
        d3 = rng.uniform(5, 50, size=(3, 6, 6))
        d3[:, 0, 0] = 0
        ti = make_instance(d3, slice_axis="first")
        p = tiers.pad_instance(ti, LADDER)
        assert p.n_slices == 24  # smallest ladder multiple of 3
        dp = np.asarray(p.durations)
        for s in range(24):
            assert np.array_equal(dp[s, :6, :6], np.asarray(ti.durations[s % 3]))

    def test_idempotent_and_off(self, monkeypatch):
        inst = synth_cvrp(9, 2, seed=4)
        p = tiers.pad_instance(inst, LADDER)
        assert tiers.pad_instance(p, LADDER) is p
        monkeypatch.setenv("VRPMS_TIERS", "off")
        assert tiers.maybe_pad(inst) is inst

    def test_pad_perm_and_canonical_giant(self):
        inst = synth_cvrp(9, 2, seed=5)
        p = tiers.pad_instance(inst, LADDER)
        perm = jnp.arange(1, 9, dtype=jnp.int32)
        padded = np.asarray(tiers.pad_perm(perm, p))
        assert list(padded) == list(range(1, 9)) + list(range(9, 16))
        real_g = jnp.asarray([0, 1, 2, 3, 4, 0, 5, 6, 7, 8, 0], jnp.int32)
        g = np.asarray(tiers.canonical_giant(p, real_g))
        assert g.shape == (15 + 2 + 1,)
        assert list(g[:11]) == list(np.asarray(real_g))
        assert sorted(g[11:]) == list(range(9, 16))


def _prep(n, opts=None, tw=False):
    rng = np.random.default_rng(n)
    pts = rng.uniform(0, 100, (n, 2))
    mat = np.sqrt(((pts[:, None] - pts[None]) ** 2).sum(-1)).tolist()
    locations = [{"id": i, "demand": 1 if i else 0} for i in range(n)]
    if tw:
        for loc in locations[1:]:
            loc["timeWindow"] = [0, 500]
    params = {
        "name": "t",
        "capacities": [10.0, 10.0],
        "start_times": [0, 0],
        "ignored_customers": [],
        "completed_customers": [],
    }
    base_opts = {"seed": 1, "population_size": 32, "iteration_count": 200}
    base_opts.update(opts or {})
    errors = []
    from service.solve import prepare_vrp

    prep = prepare_vrp("sa", params, base_opts, {}, locations, mat, errors)
    assert not errors, errors
    return prep


class TestBucketCoarsening:
    def test_same_tier_sizes_share_a_bucket(self, monkeypatch):
        monkeypatch.delenv("VRPMS_TIERS", raising=False)
        from service.jobs import _bucket_key

        k13 = _bucket_key(_prep(13))
        k15 = _bucket_key(_prep(15))
        assert k13 is not None
        assert k13 == k15  # both padded to the (16, 16) tier
        assert k13[2] == (1, 16, 16)

    def test_feature_flags_still_split(self, monkeypatch):
        monkeypatch.delenv("VRPMS_TIERS", raising=False)
        from service.jobs import _bucket_key

        assert _bucket_key(_prep(13, tw=True)) != _bucket_key(_prep(13))
        # unbatchable options force the solo path regardless of tiering
        assert _bucket_key(_prep(13, opts={"include_stats": True})) is None

    def test_tiering_off_keeps_exact_shapes(self, monkeypatch):
        monkeypatch.setenv("VRPMS_TIERS", "off")
        from service.jobs import _bucket_key

        k13 = _bucket_key(_prep(13))
        k15 = _bucket_key(_prep(15))
        assert k13 != k15
        assert k13[2] == (1, 13, 13)


class TestCompileGuard:
    def test_same_tier_back_to_back_compiles_once(self, monkeypatch):
        """The CI regression guard for the whole feature: two different
        sizes inside one tier, solved back to back through the service
        dispatch, must pay XLA compiles AT MOST once — the second solve
        reuses every program of the first (counted by the
        vrpms_compile_total source, vrpms_tpu.obs.compile)."""
        monkeypatch.delenv("VRPMS_TIERS", raising=False)
        from service.solve import solve_prepared
        from vrpms_tpu.obs import compile as compile_obs

        compile_obs.install()

        def solve(n):
            errors = []
            out = solve_prepared(_prep(n, opts={"iteration_count": 64}), errors)
            assert out is not None and not errors, errors
            return out

        solve(17)  # first sighting of the tier-24 shape may compile
        c1, _ = compile_obs.snapshot()
        solve(21)  # same tier: must be compile-free
        c2, _ = compile_obs.snapshot()
        assert c2 - c1 == 0, f"second same-tier solve paid {c2 - c1} compiles"

    def test_stats_report_compiles_on_cold_tier(self, monkeypatch):
        monkeypatch.setenv("VRPMS_TIERS", "n=20;v=2;t=1")
        from service.solve import solve_prepared

        errors = []
        out = solve_prepared(
            _prep(14, opts={"iteration_count": 64, "include_stats": True}),
            errors,
        )
        assert out is not None and not errors
        # a 20-node tier is minted fresh for this test, so the solve
        # must have paid (and reported) at least one compile
        assert out["stats"]["compile"]["count"] >= 1
