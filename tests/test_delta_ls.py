"""Delta local search: formula exactness, validity, improvement.

The delta tables claim to predict the EXACT distance change of every
(move, i, j) slot — including on asymmetric matrices, where a reversed
segment re-costs its interior legs. These tests check that claim move by
move against full evaluation, then the polish loop's contracts: valid
tours out, never worse than in, and competitive with the O(L^3)
full-evaluation steepest descent it replaces.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vrpms_tpu.core.cost import CostWeights, evaluate_giant
from vrpms_tpu.core.encoding import is_valid_giant, random_giant_batch
from vrpms_tpu.core.instance import make_instance
from vrpms_tpu.io.synth import synth_cvrp
from vrpms_tpu.moves.moves import apply_src_map
from vrpms_tpu.solvers import local_search
from vrpms_tpu.solvers.delta_ls import (
    delta_polish,
    delta_polish_batch,
    move_delta_tables,
    move_src_map,
)


def _apply_move(giants_b, t, i, j):
    """Apply one table slot via the production src-map path."""
    length = giants_b.shape[1]
    src = move_src_map(
        jnp.int32([t]), jnp.int32([i]), jnp.int32([j]), length, giants=giants_b
    )
    return apply_src_map(giants_b, src)[0]


def _asym_instance(n_customers, n_vehicles, rng, seed=0):
    n = n_customers + 1
    d = rng.uniform(5.0, 80.0, size=(n, n))
    np.fill_diagonal(d, 0.0)
    return make_instance(
        d,
        demands=[0.0] + [1.0] * n_customers,
        capacities=[float(n_customers)] * n_vehicles,
    )


def _distance(giant, inst):
    return float(evaluate_giant(giant, inst).distance)


@pytest.mark.parametrize("n_vehicles", [1, 3])
def test_deltas_match_full_eval_asymmetric(rng, n_vehicles):
    """Every finite table slot predicts the exact distance change."""
    inst = _asym_instance(9, n_vehicles, rng)
    giants = random_giant_batch(jax.random.key(3), 2, 9, n_vehicles)
    length = giants.shape[1]
    tables = np.asarray(move_delta_tables(giants, inst, mode="gather"))

    for b in range(giants.shape[0]):
        base = _distance(giants[b], inst)
        checked = 0
        for t in range(tables.shape[1]):
            for i in range(length):
                for j in range(length):
                    delta = tables[b, t, i, j]
                    if not np.isfinite(delta):
                        continue
                    moved = _apply_move(giants[b][None], t, i, j)
                    assert is_valid_giant(moved, 9, n_vehicles)
                    true_delta = _distance(moved, inst) - base
                    assert delta == pytest.approx(true_delta, abs=1e-3), (
                        f"table {t} move ({i},{j}): predicted {delta}, "
                        f"true {true_delta}"
                    )
                    checked += 1
        assert checked > 100  # the masks left a real neighborhood


def test_cap_deltas_exact_or_penalized(rng):
    """On a homogeneous fleet every capacity-table slot is either the
    exact excess change or the can't-win penalty for unmodeled moves
    (multi-node segments spanning separators, separator swaps)."""
    from vrpms_tpu.solvers.delta_ls import cap_delta_tables

    inst = synth_cvrp(13, 4, seed=9)  # tight capacity, 12 customers
    n, v = inst.n_customers, inst.n_vehicles
    giants = random_giant_batch(jax.random.key(17), 2, n, v)
    length = giants.shape[1]
    dist_t = np.asarray(move_delta_tables(giants, inst, mode="gather"))
    cap_t = np.asarray(cap_delta_tables(giants, inst, mode="gather"))
    penalty = float(2.0 * np.asarray(inst.demands).sum() + 1.0)

    n_exact = n_pen = 0
    for b in range(giants.shape[0]):
        base = float(evaluate_giant(giants[b], inst).cap_excess)
        for t in range(cap_t.shape[1]):
            for i in range(length):
                for j in range(length):
                    if not np.isfinite(dist_t[b, t, i, j]):
                        continue  # slot invalid for the move family
                    pred = cap_t[b, t, i, j]
                    if pred == pytest.approx(penalty):
                        n_pen += 1
                        continue
                    moved = _apply_move(giants[b][None], t, i, j)
                    true = float(evaluate_giant(moved, inst).cap_excess) - base
                    assert pred == pytest.approx(true, abs=1e-3), (
                        f"table {t} move ({i},{j}): predicted cap delta "
                        f"{pred}, true {true}"
                    )
                    n_exact += 1
    assert n_exact > 500 and n_pen > 50


def test_onehot_tables_match_gather(rng):
    """The TPU (one-hot/MXU) formulation of the tables must agree with
    the gather formulation: identical masks and cap deltas, distance
    within the documented bf16 rounding of the duration matrix."""
    from vrpms_tpu.solvers.delta_ls import cap_delta_tables

    inst = synth_cvrp(20, 4, seed=6)
    n, v = inst.n_customers, inst.n_vehicles
    giants = random_giant_batch(jax.random.key(19), 3, n, v)
    dist_g = np.asarray(move_delta_tables(giants, inst, mode="gather"))
    dist_h = np.asarray(move_delta_tables(giants, inst, mode="onehot"))
    assert (np.isfinite(dist_g) == np.isfinite(dist_h)).all()
    fin = np.isfinite(dist_g)
    scale = float(np.asarray(inst.durations).max())
    assert np.abs(dist_g[fin] - dist_h[fin]).max() < 0.02 * scale
    cap_g = np.asarray(cap_delta_tables(giants, inst, mode="gather"))
    cap_h = np.asarray(cap_delta_tables(giants, inst, mode="onehot"))
    np.testing.assert_allclose(cap_g, cap_h, atol=1e-4)


def test_polish_returns_valid_improved_tours(rng):
    inst = synth_cvrp(30, 5, seed=2)
    n, v = inst.n_customers, inst.n_vehicles
    giants = random_giant_batch(jax.random.key(7), 4, n, v)
    w = CostWeights.make()
    from vrpms_tpu.core.cost import objective_batch

    before = np.asarray(objective_batch(giants, inst, w))
    polished, costs, evals = delta_polish_batch(giants, inst, w)
    after = np.asarray(objective_batch(polished, inst, w))
    assert evals > 0
    for b in range(4):
        assert is_valid_giant(polished[b], n, v)
        assert after[b] <= before[b] + 1e-3
        # exact costs returned (same mode as the recheck)
        assert after[b] == pytest.approx(float(costs[b]), rel=1e-4)
    # Random tours improve, but their objective is dominated by capacity
    # penalties the distance-delta ranking does not target; the NN-seed
    # test below checks the realistic (near-feasible champion) case.
    assert after.mean() < 0.95 * before.mean()


def test_polish_improves_nn_seed_substantially(rng):
    """The production use: polishing a constructive/solver champion."""
    from vrpms_tpu.core.split import greedy_split_giant
    from vrpms_tpu.solvers.local_search import nearest_neighbor_perm

    inst = synth_cvrp(60, 8, seed=4)
    w = CostWeights.make()
    seed_giant = greedy_split_giant(nearest_neighbor_perm(inst), inst)
    before = float(evaluate_giant(seed_giant, inst).distance)
    res = delta_polish(seed_giant, inst, w)
    after = float(res.breakdown.distance)
    assert is_valid_giant(res.giant, inst.n_customers, inst.n_vehicles)
    assert after < 0.93 * before  # NN tours have crossings to remove


def test_polish_competitive_with_full_steepest_descent(rng):
    """Same neighborhood, so the polished cost should land in the same
    ballpark as the O(L^3) full evaluation descent (not necessarily
    identical: top-K acceptance vs global argmax paths can diverge)."""
    inst = synth_cvrp(16, 3, seed=5)
    n, v = inst.n_customers, inst.n_vehicles
    giants = random_giant_batch(jax.random.key(11), 1, n, v)
    w = CostWeights.make()
    full = local_search(giants[0], inst, w)
    fast = delta_polish(giants[0], inst, w)
    assert float(fast.cost) <= float(full.cost) * 1.15
    assert is_valid_giant(fast.giant, n, v)


def test_polish_on_time_windowed_instance(rng):
    """Deltas ignore TW terms by design; exact recheck must still keep
    acceptance monotone on a VRPTW instance."""
    from vrpms_tpu.io.synth import synth_vrptw

    inst = synth_vrptw(20, 4, seed=3)
    n, v = inst.n_customers, inst.n_vehicles
    giants = random_giant_batch(jax.random.key(13), 2, n, v)
    w = CostWeights.make()
    from vrpms_tpu.core.cost import objective_batch

    before = np.asarray(objective_batch(giants, inst, w))
    polished, costs, _ = delta_polish_batch(giants, inst, w)
    after = np.asarray(objective_batch(polished, inst, w))
    assert (after <= before + 1e-3).all()
    assert after.mean() < before.mean()
    for b in range(2):
        assert is_valid_giant(polished[b], n, v)
