"""Unit tests for the observability spine (vrpms_tpu.obs).

Registry/exposition behavior (counter/gauge/histogram rendering, label
escaping, the disabled no-op mode), a thread-safety smoke for the
ThreadingHTTPServer reality, the structured JSON logger with its
request-id contextvar, and the solver block-trace collector with its
convergence derivation.
"""

import io
import json
import threading

import pytest

from vrpms_tpu.obs import (
    Registry,
    collect_blocks,
    active_trace,
    convergence_summary,
    current_request_id,
    log_event,
    new_request_id,
    reset_request_id,
    set_log_stream,
    set_request_id,
)
from vrpms_tpu.obs.trace import MAX_TRACE_BLOCKS


class TestCounter:
    def test_inc_and_render(self):
        reg = Registry()
        c = reg.counter("t_total", "help text")
        c.inc()
        c.inc(2.5)
        out = reg.render()
        assert "# HELP t_total help text" in out
        assert "# TYPE t_total counter" in out
        assert "t_total 3.5" in out

    def test_labels_create_series(self):
        reg = Registry()
        c = reg.counter("r_total", "h", labels=("route", "outcome"))
        c.labels(route="/api", outcome="ok").inc()
        c.labels(route="/api", outcome="ok").inc()
        c.labels(route="/api", outcome="error").inc()
        out = reg.render()
        assert 'r_total{route="/api",outcome="ok"} 2' in out
        assert 'r_total{route="/api",outcome="error"} 1' in out

    def test_wrong_labels_rejected(self):
        reg = Registry()
        c = reg.counter("x_total", "h", labels=("a",))
        with pytest.raises(ValueError):
            c.labels(b="1")

    def test_negative_increment_rejected(self):
        reg = Registry()
        c = reg.counter("n_total", "h")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_duplicate_name_rejected(self):
        reg = Registry()
        reg.counter("dup_total", "h")
        with pytest.raises(ValueError):
            reg.gauge("dup_total", "h")

    def test_label_value_escaping(self):
        reg = Registry()
        c = reg.counter("e_total", "h", labels=("v",))
        c.labels(v='a"b\\c\nd').inc()
        out = reg.render()
        assert 'v="a\\"b\\\\c\\nd"' in out


class TestGauge:
    def test_set_inc_dec(self):
        reg = Registry()
        g = reg.gauge("g", "h")
        g.set(4)
        g.inc()
        g.dec(2)
        assert g.value == 3
        assert "g 3" in reg.render()


class TestHistogram:
    def test_cumulative_buckets_sum_count(self):
        reg = Registry()
        h = reg.histogram("lat", "h", buckets=(1, 5, 10))
        for v in (0.5, 3, 7, 100):
            h.observe(v)
        out = reg.render()
        assert 'lat_bucket{le="1"} 1' in out
        assert 'lat_bucket{le="5"} 2' in out
        assert 'lat_bucket{le="10"} 3' in out
        assert 'lat_bucket{le="+Inf"} 4' in out
        assert "lat_count 4" in out
        assert "lat_sum 110.5" in out

    def test_labelled_histogram(self):
        reg = Registry()
        h = reg.histogram("s", "h", labels=("algo",), buckets=(1,))
        h.labels(algo="sa").observe(0.5)
        out = reg.render()
        assert 's_bucket{algo="sa",le="1"} 1' in out
        assert 's_count{algo="sa"} 1' in out

    def test_inf_bucket_always_appended(self):
        reg = Registry()
        h = reg.histogram("b", "h", buckets=(2,))
        assert h.buckets[-1] == float("inf")


class TestDisabledRegistry:
    def test_all_instruments_noop(self):
        reg = Registry(enabled=False)
        c = reg.counter("c_total", "h")
        g = reg.gauge("g", "h")
        h = reg.histogram("h", "h", buckets=(1,))
        c.inc()
        g.set(9)
        h.observe(0.5)
        out = reg.render()
        assert "c_total 0" in out
        assert "g 0" in out
        assert "h_count 0" in out


class TestThreadSafety:
    def test_concurrent_increments_exact(self):
        """8 writer threads on shared + per-thread label series: final
        counts must be exact (the router is a ThreadingHTTPServer)."""
        reg = Registry()
        c = reg.counter("smoke_total", "h", labels=("who",))
        h = reg.histogram("smoke_lat", "h", buckets=(0.5, 1.0))
        n_threads, n_iter = 8, 1000

        def work(i):
            for _ in range(n_iter):
                c.labels(who="all").inc()
                c.labels(who=str(i)).inc()
                h.observe(0.25)

        threads = [
            threading.Thread(target=work, args=(i,))
            for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.labels(who="all").value == n_threads * n_iter
        for i in range(n_threads):
            assert c.labels(who=str(i)).value == n_iter
        assert f"smoke_lat_count {n_threads * n_iter}" in reg.render()


class TestStructuredLogging:
    def test_one_json_object_per_line(self):
        buf = io.StringIO()
        prev = set_log_stream(buf)
        try:
            log_event("test.event", a=1, b="x", dropped=None)
        finally:
            set_log_stream(prev)
        (line,) = buf.getvalue().strip().splitlines()
        rec = json.loads(line)
        assert rec["event"] == "test.event"
        assert rec["a"] == 1 and rec["b"] == "x"
        assert "dropped" not in rec
        assert "ts" in rec

    def test_request_id_contextvar_attached(self):
        buf = io.StringIO()
        prev = set_log_stream(buf)
        rid = new_request_id()
        token = set_request_id(rid)
        try:
            assert current_request_id() == rid
            log_event("test.corr")
        finally:
            reset_request_id(token)
            set_log_stream(prev)
        assert current_request_id() is None
        rec = json.loads(buf.getvalue())
        assert rec["requestId"] == rid

    def test_request_ids_unique_and_short(self):
        ids = {new_request_id() for _ in range(100)}
        assert len(ids) == 100
        assert all(len(i) == 12 for i in ids)


class TestBlockTrace:
    def test_inactive_by_default(self):
        assert active_trace() is None
        with collect_blocks(enabled=False) as tr:
            assert tr is None
            assert active_trace() is None

    def test_records_cumulative_entries(self):
        with collect_blocks() as tr:
            assert active_trace() is tr
            tr.record([5.0, 3.0], iters=128, evals_per_iter=4)
            tr.record([2.5], iters=128, evals_per_iter=4)
        assert active_trace() is None
        assert [b["evals"] for b in tr.blocks] == [512, 1024]
        assert [b["bestCost"] for b in tr.blocks] == [3.0, 2.5]
        assert tr.blocks[0]["wallMs"] <= tr.blocks[1]["wallMs"]

    def test_truncation_keeps_eval_accounting(self):
        with collect_blocks() as tr:
            for _ in range(MAX_TRACE_BLOCKS + 10):
                tr.record([1.0], iters=1, evals_per_iter=2)
        assert len(tr.blocks) == MAX_TRACE_BLOCKS
        assert tr.truncated

    def test_convergence_summary(self):
        blocks = [
            {"wallMs": 100.0, "bestCost": 50.0, "evals": 1000},
            {"wallMs": 110.0, "bestCost": 50.0, "evals": 2000},
            {"wallMs": 120.0, "bestCost": 40.0, "evals": 3000},
        ]
        conv = convergence_summary(blocks)
        assert conv["blocks"] == 3
        assert conv["firstBlockMs"] == 100.0
        assert conv["timeToFirstImprovementMs"] == 120.0
        # block 0: 100 ms for 1000 evals; steady: 20 ms for 2000 more
        assert conv["msPerKEvalFirstBlock"] == 100.0
        assert conv["msPerKEvalSteady"] == 10.0

    def test_convergence_summary_edge_cases(self):
        assert convergence_summary([]) is None
        conv = convergence_summary(
            [{"wallMs": 5.0, "bestCost": 1.0, "evals": 10}]
        )
        assert conv["timeToFirstImprovementMs"] is None
        assert "msPerKEvalSteady" not in conv


class TestRunBlockedTrace:
    """The solver loop records into an active collector with zero
    jit-graph changes — exercised through run_blocked itself with a
    numpy 'device' state."""

    def test_deadline_path_records_blocks(self):
        import numpy as np

        from vrpms_tpu.solvers.common import run_blocked

        def step(state, nb, start):
            return state - 0.1 * nb

        with collect_blocks() as tr:
            state, done = run_blocked(
                step, np.float32(10.0), 256, 128, deadline_s=60.0,
                sync=lambda s: s, evals_per_iter=8,
            )
        assert done == 256
        assert len(tr.blocks) >= 1
        assert tr.blocks[-1]["evals"] == 256 * 8
        costs = [b["bestCost"] for b in tr.blocks]
        assert costs == sorted(costs, reverse=True)

    def test_single_block_path_records_once(self):
        import numpy as np

        from vrpms_tpu.solvers.common import run_blocked

        with collect_blocks() as tr:
            _, done = run_blocked(
                lambda s, nb, start: s, np.float32(3.0), 500, 512,
                deadline_s=None, sync=lambda s: s, evals_per_iter=2,
            )
        assert done == 500
        assert len(tr.blocks) == 1
        assert tr.blocks[0]["evals"] == 1000

    def test_no_collector_records_nothing(self):
        import numpy as np

        from vrpms_tpu.solvers.common import run_blocked

        _, done = run_blocked(
            lambda s, nb, start: s, np.float32(3.0), 128, 128,
            deadline_s=30.0, sync=lambda s: s, evals_per_iter=2,
        )
        assert done == 128
        assert active_trace() is None
