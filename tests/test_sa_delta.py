"""Fused delta-step kernel (kernels.sa_delta): interpret-mode equivalence
against the XLA proposal/apply/eval reference, plus multi-step state
integrity. The kernel also passed a bit-exact compiled-vs-interpret check
on a real v5e (see BASELINE.md round 3); these CPU tests pin the math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vrpms_tpu.core.cost import (
    CostWeights,
    _cap_excess_hot,
    _legs_hot,
    _rid_batch,
)
from vrpms_tpu.io.synth import synth_cvrp
from vrpms_tpu.moves import knn_table
from vrpms_tpu.moves.moves import (
    _segment_src_map,
    apply_src_map,
    presample_move_params,
    window_from_params,
)
from vrpms_tpu.solvers.sa import SAParams, _pow2_at_least, initial_giants

pytest.importorskip("jax.experimental.pallas")

from vrpms_tpu.kernels import sa_delta as K  # noqa: E402


def _setup(n=30, v=5, batch=64, seed=3, knn_k=8):
    inst = synth_cvrp(n, v, seed=seed)
    w = CostWeights.make()
    giants = initial_giants(jax.random.key(0), batch, inst, SAParams(), "onehot")
    b, length = giants.shape
    lhat = _pow2_at_least(length)
    nhat = 128
    knn = knn_table(inst.durations[0], knn_k)
    d_np = np.zeros((nhat, nhat), np.float32)
    d_np[: inst.n_nodes, : inst.n_nodes] = np.asarray(inst.durations[0])
    kf = np.zeros((nhat, knn_k), np.float32)
    kf[: inst.n_nodes] = np.asarray(knn, np.float32)
    prev_oh, _, legs, _ = _legs_hot(giants, inst)
    dist = legs.sum(axis=1)[None]
    cape = _cap_excess_hot(prev_oh, _rid_batch(giants), inst)[None]
    gt_t = jnp.zeros((lhat, b), jnp.int32).at[:length].set(giants.T)
    dp = np.asarray(inst.demands)[np.asarray(giants)]
    dp_t = jnp.zeros((lhat, b), jnp.float32).at[:length].set(jnp.asarray(dp).T)
    return (
        inst, w, giants, length, lhat, knn,
        jnp.asarray(d_np, jnp.bfloat16), jnp.asarray(kf),
        gt_t, dp_t, dist, cape,
    )


class TestDeltaStepKernel:
    def test_single_step_matches_xla_reference(self, rng):
        (inst, w, giants, L, lhat, knn, d_bf16, knn_f,
         gt_t, dp_t, dist, cape) = _setup()
        b = giants.shape[0]
        i, r, mt, m, u = (
            a[0] for a in presample_move_params(jax.random.key(7), b, L, 1, 8)
        )
        temp = 5.0
        cap0 = float(np.asarray(inst.capacities)[0])
        scal = jnp.asarray([[temp, cap0, float(w.cap)]], jnp.float32)
        bc = dist + w.cap * cape
        gt2, dp2, dist2, cape2, bt2, bc2 = K.delta_step(
            gt_t, dp_t, dist, cape, gt_t, bc,
            i[None], r[None], mt[None], m[None], u[None],
            d_bf16, knn_f, scal,
            length=L, tile_b=b, has_knn=True, interpret=True,
        )
        # the XLA reference: identical proposal decode + full evaluation
        lo, hi, mtc, mc = window_from_params(i, r, mt, m, giants, knn, "gather")
        src = _segment_src_map(lo, hi, mtc, mc, L)
        cands = apply_src_map(giants, src, "gather")
        prev_oh, _, legs, _ = _legs_hot(cands, inst)
        dist_c = legs.sum(axis=1)
        cape_c = _cap_excess_hot(prev_oh, _rid_batch(cands), inst)
        cur = dist[0] + w.cap * cape[0]
        cnd = dist_c + w.cap * cape_c
        accept = (cnd < cur) | (u < jnp.exp(jnp.minimum((cur - cnd) / temp, 0.0)))
        g_ref = jnp.where(accept[:, None], cands, giants)
        assert (np.asarray(gt2[:L].T) == np.asarray(g_ref)).all()
        np.testing.assert_allclose(
            np.asarray(dist2[0]),
            np.asarray(jnp.where(accept, dist_c, dist[0])),
            rtol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(cape2[0]),
            np.asarray(jnp.where(accept, cape_c, cape[0])),
            rtol=1e-5,
        )

    def test_many_steps_zero_drift_and_valid_tours(self):
        # 120 chained kernel steps: the incremental dist/cape state must
        # match a from-scratch evaluation EXACTLY (no fp drift at this
        # scale), tours must stay permutations, dp must track demands
        (inst, w, giants, L, lhat, knn, d_bf16, knn_f,
         gt_t, dp_t, dist, cape) = _setup()
        b = giants.shape[0]
        cap0 = float(np.asarray(inst.capacities)[0])
        scal = jnp.asarray([[5.0, cap0, float(w.cap)]], jnp.float32)
        bc = dist + w.cap * cape
        best_t = gt_t
        i_s, r_s, mt_s, m_s, u_s = presample_move_params(
            jax.random.key(9), b, L, 120, 8
        )
        for step in range(120):
            gt_t, dp_t, dist, cape, best_t, bc = K.delta_step(
                gt_t, dp_t, dist, cape, best_t, bc,
                i_s[step][None], r_s[step][None], mt_s[step][None],
                m_s[step][None], u_s[step][None],
                d_bf16, knn_f, scal,
                length=L, tile_b=b, has_knn=True, interpret=True,
            )
        g = gt_t[:L].T
        gh = np.asarray(g)
        for row in gh:
            assert sorted(x for x in row if x) == list(
                range(1, inst.n_customers + 1)
            )
        prev_oh, _, legs, _ = _legs_hot(g, inst)
        np.testing.assert_allclose(
            np.asarray(dist[0]), np.asarray(legs.sum(axis=1)), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(cape[0]),
            np.asarray(_cap_excess_hot(prev_oh, _rid_batch(g), inst)),
            rtol=1e-5, atol=1e-5,
        )
        dp_ref = np.asarray(inst.demands)[gh]
        np.testing.assert_allclose(np.asarray(dp_t[:L].T), dp_ref, atol=1e-6)
        # best-so-far never above the running cost seen at any step end
        assert (np.asarray(bc[0]) <= np.asarray(dist[0] + w.cap * cape[0]) + 1e-4).all()

    def test_uniform_window_without_knn(self):
        (inst, w, giants, L, lhat, knn, d_bf16, knn_f,
         gt_t, dp_t, dist, cape) = _setup()
        b = giants.shape[0]
        i, r, mt, m, u = (
            a[0] for a in presample_move_params(jax.random.key(11), b, L, 1, 0)
        )
        cap0 = float(np.asarray(inst.capacities)[0])
        scal = jnp.asarray([[5.0, cap0, float(w.cap)]], jnp.float32)
        bc = dist + w.cap * cape
        gt2, *_ = K.delta_step(
            gt_t, dp_t, dist, cape, gt_t, bc,
            i[None], r[None], mt[None], m[None], u[None],
            d_bf16, knn_f, scal,
            length=L, tile_b=b, has_knn=False, interpret=True,
        )
        lo, hi, mtc, mc = window_from_params(i, r, mt, m, giants, None, "gather")
        src = _segment_src_map(lo, hi, mtc, mc, L)
        cands = apply_src_map(giants, src, "gather")
        prev_oh, _, legs, _ = _legs_hot(cands, inst)
        dist_c = legs.sum(axis=1)
        cape_c = _cap_excess_hot(prev_oh, _rid_batch(cands), inst)
        cur = dist[0] + w.cap * cape[0]
        cnd = dist_c + w.cap * cape_c
        accept = (cnd < cur) | (u < jnp.exp(jnp.minimum((cur - cnd) / 5.0, 0.0)))
        g_ref = jnp.where(accept[:, None], cands, giants)
        assert (np.asarray(gt2[:L].T) == np.asarray(g_ref)).all()


class TestSolveSaDelta:
    """The solve-level delta driver under interpret mode (CPU CI): block
    composition must use GLOBAL iteration offsets — a block that
    restarts its schedule/RNG at 0 replays identical proposals at
    replayed temperatures (the exact bug class this pins)."""

    def test_driver_matches_manual_block_composition(self, monkeypatch):
        import os

        monkeypatch.setenv("VRPMS_DELTA_INTERPRET", "1")
        from vrpms_tpu.core.cost import CostWeights
        from vrpms_tpu.solvers.sa import (
            _delta_prep,
            _delta_resync_fn,
            _sa_delta_block_fn,
            _temps_from_scale,
            _mean_fn,
            solve_sa_delta,
        )

        inst = synth_cvrp(20, 4, seed=2)
        w = CostWeights.make()
        params = SAParams(n_chains=128, n_iters=700)  # 2 blocks: 512 + 188
        res = solve_sa_delta(inst, key=5, params=params)
        # manual composition with EXPLICIT global offsets
        key = jax.random.key(5)
        k_init, k_run = jax.random.split(key)
        from vrpms_tpu.solvers.sa import _pow2_at_least, _sa_prep_fn

        giants, _c, mean = _sa_prep_fn(128, "onehot")(k_init, inst, w)
        t0, t1 = _temps_from_scale(float(mean), params)
        b, length = giants.shape
        lhat = _pow2_at_least(length)
        nhat = 128
        knn = knn_table(inst.durations[0], params.knn_k)
        d_np = np.zeros((nhat, nhat), np.float32)
        d_np[: inst.n_nodes, : inst.n_nodes] = np.asarray(inst.durations[0])
        kf = np.zeros((nhat, knn.shape[1]), np.float32)
        kf[: inst.n_nodes] = np.asarray(knn, np.float32)
        cap0 = float(np.asarray(inst.capacities)[0])
        scal2 = jnp.asarray([[cap0, float(w.cap)]], jnp.float32)
        gt_t, dp_t, dist, cape = _delta_prep(
            giants, inst, w, lhat, nhat, 128, 1.0, True
        )
        state = (gt_t, dp_t, dist, cape, gt_t, dist + w.cap * cape)
        horizon = jnp.float32(700)
        for start, nb in ((0, 512), (512, 188)):
            state = _sa_delta_block_fn(nb, length, 128, True, True)(
                state, k_run, jnp.asarray(d_np, jnp.bfloat16),
                jnp.asarray(kf), scal2, jnp.float32(t0), jnp.float32(t1),
                jnp.int32(start), horizon,
            )
            # the driver resyncs between blocks; mirror it
            dist2, cape2 = _delta_resync_fn(length, True)(state[0], inst, w)
            state = (state[0], state[1], dist2, cape2, state[4], state[5])
        # mirror the driver's exact best-pool re-rank (ADVICE r3: the raw
        # kernel tracker carries drift; selection goes by resynced cost)
        bd2, bc2 = _delta_resync_fn(length, True)(state[4], inst, w)
        best_exact = bd2 + w.cap * bc2
        champ = int(jnp.argmin(best_exact[0]))
        want_giant = np.asarray(state[4][:length, champ])
        # the driver re-prices its champion exactly (f32) while best_c is
        # the kernel's bf16-table cost, so compare the TOURS (identical
        # trajectories) and the costs only to bf16 tolerance
        assert (np.asarray(res.giant) == want_giant).all()
        assert np.isclose(
            float(res.cost), float(state[5][0][champ]), rtol=5e-3
        )
