"""Unit + property tests for the cost kernels (SURVEY.md §4 items 1-2)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from vrpms_tpu.core import make_instance, evaluate_giant
from vrpms_tpu.core.cost import evaluate_batch, total_cost, CostWeights
from vrpms_tpu.core.encoding import (
    giant_length,
    random_giant,
    random_giant_batch,
    routes_from_giant,
    giant_from_routes,
    is_valid_giant,
)
from tests.oracle import naive_eval


def tiny_instance(**kw):
    # 1 depot + 3 customers, asymmetric durations, hand-checkable.
    d = [
        [0.0, 10.0, 20.0, 30.0],
        [12.0, 0.0, 5.0, 9.0],
        [21.0, 6.0, 0.0, 4.0],
        [33.0, 8.0, 3.0, 0.0],
    ]
    defaults = dict(demands=[0, 4, 5, 6], capacities=[10, 10])
    defaults.update(kw)
    return make_instance(d, **defaults)


def random_instance(rng, n=8, v=3, tw=False, t_slices=1):
    d = rng.uniform(1, 50, size=(t_slices, n, n))
    kw = dict(
        slice_axis="first",
        demands=rng.uniform(1, 5, size=n),
        capacities=rng.uniform(8, 15, size=v),
        service=rng.uniform(0, 3, size=n),
        start_times=rng.uniform(0, 5, size=v),
    )
    if tw:
        kw["ready"] = rng.uniform(0, 40, size=n)
        kw["due"] = kw["ready"] + rng.uniform(10, 60, size=n)
    return make_instance(d, **kw)


class TestFastPath:
    def test_hand_checked_distance(self):
        inst = tiny_instance()
        giant = jnp.asarray([0, 1, 2, 0, 3, 0], dtype=jnp.int32)
        c = evaluate_giant(giant, inst)
        # route 0: 0->1->2->0 = 10+5+21 = 36 ; route 1: 0->3->0 = 30+33 = 63
        assert np.isclose(float(c.distance), 36 + 63)
        np.testing.assert_allclose(np.asarray(c.route_durations), [36.0, 63.0])
        assert float(c.cap_excess) == 0.0
        assert float(c.tw_lateness) == 0.0
        assert np.isclose(float(c.duration_max), 63.0)
        assert np.isclose(float(c.duration_sum), 99.0)

    def test_capacity_excess(self):
        inst = tiny_instance(capacities=[8, 5])
        giant = jnp.asarray([0, 1, 2, 0, 3, 0], dtype=jnp.int32)
        c = evaluate_giant(giant, inst)
        # loads: 9 vs 8 -> +1 ; 6 vs 5 -> +1
        assert np.isclose(float(c.cap_excess), 2.0)
        w = CostWeights.make(cap=100.0)
        assert np.isclose(float(total_cost(c, w)), 99.0 + 200.0)

    def test_empty_route_is_free(self):
        inst = tiny_instance(capacities=[30, 30])
        all_in_one = jnp.asarray([0, 1, 2, 3, 0, 0], dtype=jnp.int32)
        c = evaluate_giant(all_in_one, inst)
        # 0->1->2->3->0 = 10+5+4+33 = 52; second vehicle unused
        assert np.isclose(float(c.distance), 52.0)
        np.testing.assert_allclose(np.asarray(c.route_durations), [52.0, 0.0])


class TestTimeWindows:
    def test_hand_checked_waiting_and_lateness(self):
        inst = tiny_instance(
            capacities=[30],
            ready=[0, 15, 0, 0],
            due=[1000, 100, 16, 100],
            service=[0, 2, 2, 2],
        )
        giant = jnp.asarray([0, 1, 2, 3, 0], dtype=jnp.int32)
        c = evaluate_giant(giant, inst)
        # depart depot t=0; arrive 1 at max(10, 15)=15 (wait), late 0
        # depart 1 at 17; arrive 2 at 17+5=22, late 22-16=6
        # depart 2 at 24; arrive 3 at 24+4=28, late 0
        # depart 3 at 30; arrive depot at 30+33=63
        assert np.isclose(float(c.tw_lateness), 6.0)
        assert np.isclose(float(c.distance), 10 + 5 + 4 + 33)
        np.testing.assert_allclose(np.asarray(c.route_durations), [63.0])

    def test_parallel_routes_reset_clock(self):
        # Route 1 must start at its own shift start, not after route 0.
        inst = tiny_instance(
            ready=[0, 0, 0, 0],
            due=[1000, 1000, 1000, 35],
            start_times=[0.0, 2.0],
        )
        giant = jnp.asarray([0, 1, 2, 0, 3, 0], dtype=jnp.int32)
        c = evaluate_giant(giant, inst)
        # vehicle 1 departs at t=2, arrives 3 at 2+30=32 < due 35 -> no lateness
        assert np.isclose(float(c.tw_lateness), 0.0)
        np.testing.assert_allclose(np.asarray(c.route_durations), [36.0, 63.0])


class TestTimeDependent:
    def test_slice_selection(self):
        # Two slices of 30 min: first slice doubles every duration.
        base = np.array(
            [
                [0.0, 10, 20, 30],
                [12, 0, 5, 9],
                [21, 6, 0, 4],
                [33, 8, 3, 0],
            ]
        )
        d = np.stack([2 * base, base])  # [T, N, N]
        inst = make_instance(d, n_vehicles=1, slice_minutes=30.0)
        giant = jnp.asarray([0, 1, 2, 3, 0], dtype=jnp.int32)
        c = evaluate_giant(giant, inst)
        # depart 0 at t=0 (slice 0): travel 20 -> arrive 1 at 20
        # depart 1 at 20 (slice 0): travel 10 -> arrive 2 at 30
        # depart 2 at 30 (slice 1): travel 4  -> arrive 3 at 34
        # depart 3 at 34 (slice 1): travel 33 -> arrive 0 at 67
        assert np.isclose(float(c.distance), 20 + 10 + 4 + 33)
        np.testing.assert_allclose(np.asarray(c.route_durations), [67.0])


class TestPropertyVsOracle:
    @pytest.mark.parametrize("tw", [False, True])
    @pytest.mark.parametrize("t_slices", [1, 3])
    def test_matches_naive_eval(self, rng, tw, t_slices):
        for trial in range(10):
            n = int(rng.integers(3, 12))
            v = int(rng.integers(1, 4))
            inst = random_instance(rng, n=n, v=v, tw=tw, t_slices=t_slices)
            key = jax.random.key(trial)
            giant = random_giant(key, n - 1, v)
            got = evaluate_giant(giant, inst)
            want = naive_eval(giant, inst)
            np.testing.assert_allclose(
                float(got.distance), want["distance"], rtol=1e-5
            )
            np.testing.assert_allclose(
                float(got.cap_excess), want["cap_excess"], rtol=1e-5, atol=1e-5
            )
            np.testing.assert_allclose(
                float(got.tw_lateness), want["tw_lateness"], rtol=1e-4, atol=1e-3
            )
            np.testing.assert_allclose(
                np.asarray(got.route_durations),
                want["route_durations"],
                rtol=1e-5,
                atol=1e-3,
            )

    def test_batch_matches_single(self, rng):
        inst = random_instance(rng, n=10, v=3)
        giants = random_giant_batch(jax.random.key(7), 16, 9, 3)
        batch = evaluate_batch(giants, inst)
        for b in range(16):
            single = evaluate_giant(giants[b], inst)
            np.testing.assert_allclose(
                float(batch.distance[b]), float(single.distance), rtol=1e-6
            )


class TestEncoding:
    def test_random_giant_valid(self):
        for seed in range(5):
            g = random_giant(jax.random.key(seed), 9, 3)
            assert is_valid_giant(g, 9, 3)

    def test_roundtrip(self):
        routes = [[3, 1], [], [2, 5, 4]]
        g = giant_from_routes(routes, 5, 3)
        assert is_valid_giant(g, 5, 3)
        assert routes_from_giant(g) == routes

    def test_lengths(self):
        assert giant_length(5, 3) == 9
        g = giant_from_routes([[1, 2, 3, 4, 5]], 5, 1)
        assert g.shape == (7,)


class TestTDFactorization:
    """The time-profile factorization (Instance.td_rank) and the
    factorized TD hot path it unlocks (core.cost._td_hot_batch)."""

    def _mk(self, rng, slices, n, v=5):
        dem = np.concatenate([[0], rng.integers(1, 9, n - 1)])
        return make_instance(
            slices, demands=dem, capacities=[40.0] * v,
            slice_axis="first", slice_minutes=45.0,
        )

    def test_rank_detection(self, rng):
        n, t = 20, 6
        base = rng.uniform(5, 60, (n, n))
        np.fill_diagonal(base, 0)
        f1 = 1.0 + 0.3 * np.sin(np.linspace(0, 2 * np.pi, t, endpoint=False))
        inst1 = self._mk(rng, base[None] * f1[:, None, None], n)
        assert inst1.td_rank == 1
        base2 = rng.uniform(1, 10, (n, n))
        np.fill_diagonal(base2, 0)
        two = np.maximum(
            base[None] * f1[:, None, None]
            + base2[None] * (1 + 0.2 * rng.standard_normal(t))[:, None, None],
            0.0,
        )
        two[:, 0, 0] = 0.0
        inst2 = self._mk(rng, two, n)
        assert inst2.td_rank == 2
        full = rng.uniform(5, 60, (t, n, n))
        assert self._mk(rng, full, n).td_rank == 0  # no exact low-rank form

    def test_factorized_hot_path_matches_td_eval(self, rng):
        from vrpms_tpu.core.cost import CostWeights, _td_eval, _td_hot_batch, total_cost
        from vrpms_tpu.core.encoding import random_giant_batch

        n, t = 24, 8
        base = rng.uniform(5, 60, (n, n))
        np.fill_diagonal(base, 0)
        f1 = 1.0 + 0.3 * np.sin(np.linspace(0, 2 * np.pi, t, endpoint=False))
        inst = self._mk(rng, base[None] * f1[:, None, None], n)
        assert inst.td_rank == 1
        w = CostWeights.make()
        giants = random_giant_batch(jax.random.key(0), 12, n - 1, 5)
        hot = _td_hot_batch(giants, inst, w)
        ref = jnp.stack(
            [total_cost(_td_eval(giants[i], inst), w) for i in range(12)]
        )
        # bf16 table rounding is the hot paths' shared precision budget
        np.testing.assert_allclose(np.asarray(hot), np.asarray(ref), rtol=5e-3)

    def test_factorization_reconstructs_exactly(self, rng):
        n, t = 16, 5
        base = rng.uniform(5, 60, (n, n))
        np.fill_diagonal(base, 0)
        f1 = 0.5 + rng.uniform(0.1, 1.0, t)
        inst = self._mk(rng, base[None] * f1[:, None, None], n)
        assert inst.td_rank >= 1
        recon = np.einsum(
            "rt,rnm->tnm",
            np.asarray(inst.td_factors),
            np.asarray(inst.td_basis),
        )
        np.testing.assert_allclose(
            recon, np.asarray(inst.durations), rtol=1e-4, atol=1e-3
        )


class TestBestFeasiblePool:
    def test_picks_min_distance_feasible_member(self):
        import numpy as np

        from vrpms_tpu.core.cost import best_feasible_pool, tw_components_batch
        from vrpms_tpu.io.synth import synth_vrptw
        from vrpms_tpu.core.encoding import random_giant_batch

        inst = synth_vrptw(12, 3, seed=4)
        pool = random_giant_batch(jax.random.key(0), 16, inst.n_customers,
                                  inst.n_vehicles)
        out = best_feasible_pool(pool, inst)
        dist, cape, late, _, _ = map(
            np.asarray, tw_components_batch(pool, inst)
        )
        feas = (cape == 0.0) & (late == 0.0)
        if feas.any():
            assert out == float(dist[feas].min())
        else:
            assert out is None

    def test_none_pool_and_infeasible(self):
        import numpy as np

        from vrpms_tpu.core import make_instance
        from vrpms_tpu.core.cost import best_feasible_pool
        from vrpms_tpu.core.encoding import giant_from_routes

        assert best_feasible_pool(None, object()) is None
        # one-customer instance with an impossible window: the only
        # tour is late, so no feasible member exists
        d = np.array([[0.0, 5.0], [5.0, 0.0]])
        inst = make_instance(
            d, demands=[0, 1], capacities=[10.0],
            ready=[0.0, 0.0], due=[100.0, 1.0], service=[0.0, 1.0],
        )
        g = giant_from_routes([[1]], 1, 1)
        assert best_feasible_pool(g[None], inst) is None
