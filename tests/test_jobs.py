"""End-to-end async jobs API tests (submit -> poll -> done) over real
HTTP against the in-memory store, under JAX_PLATFORMS=cpu.

Covers the ISSUE-2 acceptance criteria: the async lifecycle against the
store seam, deadline-spent-in-queue expiry, concurrent mixed-shape
submits (with same-shape jobs actually merging into one batched
launch), queue-full backpressure as 429 + Retry-After (never a hung
connection), and drain-on-shutdown failing queued jobs cleanly.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import store.memory as mem
from service import jobs as jobs_mod
from service.app import serve


@pytest.fixture(scope="module")
def server():
    import os

    os.environ["VRPMS_STORE"] = "memory"
    # a fresh scheduler for this module (another test module may have
    # built one under different env)
    jobs_mod.shutdown_scheduler()
    srv = serve(port=0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    jobs_mod.shutdown_scheduler()


@pytest.fixture(autouse=True)
def seeded():
    mem.reset()
    rng = np.random.default_rng(11)
    for key, n in (("locs7", 7), ("locs10", 10)):
        pts = rng.uniform(0, 100, size=(n, 2))
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        mem.seed_locations(
            key, [{"id": i, "demand": 2 if i else 0} for i in range(n)]
        )
        mem.seed_durations(key, d.tolist())
    yield


def post(base, path, body):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=600) as resp:
            return resp.status, json.loads(resp.read()), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), e.headers


def get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=60) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def job_body(key="locs7", n=7, **over):
    body = {
        "problem": "vrp",
        "algorithm": "sa",
        "solutionName": f"job-{key}",
        "solutionDescription": "t",
        "locationsKey": key,
        "durationsKey": key,
        "capacities": [2 * n] * 3,
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": 1,
        "iterationCount": 300,
        "populationSize": 16,
    }
    body.update(over)
    return body


def poll_until(base, job_id, terminal=("done", "failed"), timeout=120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status, resp = get(base, f"/api/jobs/{job_id}")
        assert status == 200, resp
        job = resp["job"]
        if job["status"] in terminal:
            return job
        time.sleep(0.05)
    raise AssertionError(f"job {job_id} never reached {terminal}")


class TestLifecycle:
    def test_submit_poll_done(self, server):
        status, resp, _ = post(server, "/api/jobs", job_body())
        assert status == 202, resp
        assert resp["success"] is True
        job_id = resp["jobId"]
        assert resp["status"] in ("queued", "running", "done")
        job = poll_until(server, job_id)
        assert job["status"] == "done", job
        assert job["problem"] == "vrp" and job["algorithm"] == "sa"
        msg = job["message"]
        visited = sorted(
            c for v in msg["vehicles"] for c in v["tour"][1:-1]
        )
        assert visited == [1, 2, 3, 4, 5, 6]
        # lifecycle bookkeeping is part of the record
        assert job["queueWaitMs"] is not None and job["queueWaitMs"] >= 0
        assert job["batchSize"] >= 1
        assert job["finishedAt"] >= job["startedAt"] >= job["submittedAt"]
        assert job["requestId"]

    def test_async_bf_carries_certificate(self, server):
        status, resp, _ = post(
            server, "/api/jobs", job_body(algorithm="bf")
        )
        assert status == 202, resp
        job = poll_until(server, resp["jobId"])
        assert job["status"] == "done", job
        assert job["message"]["exact"]["proven"] is True

    def test_bad_submit_is_400(self, server):
        status, resp, _ = post(server, "/api/jobs", {"problem": "vrp"})
        assert status == 400
        assert resp["success"] is False
        reasons = {e["reason"] for e in resp["errors"]}
        assert "'algorithm' must be one of ga|sa|aco|bf" in reasons
        # a parse failure inside a valid problem/algorithm pair
        status, resp, _ = post(
            server, "/api/jobs", {"problem": "vrp", "algorithm": "sa"}
        )
        assert status == 400
        assert any(
            "solutionName" in e["reason"] for e in resp["errors"]
        )

    def test_unknown_job_is_404(self, server):
        status, resp = get(server, "/api/jobs/no-such-job")
        assert status == 404
        assert resp["success"] is False
        assert resp["errors"][0]["what"] == "Not found"

    def test_failed_job_reports_errors(self, server):
        # nonsense solver option passes parsing but fails in the solver
        # dispatch — the job must land `failed` with the envelope entry
        status, resp, _ = post(
            server, "/api/jobs", job_body(ilsRounds=-3)
        )
        assert status == 202, resp
        job = poll_until(server, resp["jobId"])
        assert job["status"] == "failed", job
        assert any(
            "non-negative integer" in e["reason"] for e in job["errors"]
        )


class TestDeadlineInQueue:
    def test_deadline_spent_in_queue_fails_cleanly(self, server):
        # occupy the worker with a ~2s solve, then submit a job whose
        # whole budget is 50ms: its queue wait alone spends the budget,
        # so it must FAIL without ever starting
        blocker = job_body(
            iterationCount=500_000, populationSize=64, timeLimit=2,
            seed=9,
        )
        status, resp, _ = post(server, "/api/jobs", blocker)
        assert status == 202, resp
        blocker_id = resp["jobId"]
        time.sleep(0.3)  # let the worker pick the blocker up
        status, resp, _ = post(
            server, "/api/jobs", job_body(timeLimit=0.05, seed=10)
        )
        assert status == 202, resp
        doomed = poll_until(server, resp["jobId"])
        assert doomed["status"] == "failed", doomed
        assert doomed["errors"][0]["what"] == "Deadline exceeded"
        assert "queue" in doomed["errors"][0]["reason"]
        # the blocker itself completes fine
        assert poll_until(server, blocker_id)["status"] == "done"


class TestConcurrentMixedShapes:
    def test_mixed_shape_submits_all_complete_and_batch(self, server):
        # 8 concurrent submits across two shapes: every job completes
        # with its own instance's customers, and same-shape jobs that
        # queued behind the busy worker merge into batched launches
        specs = [("locs7", 7), ("locs10", 10)] * 4
        results = [None] * len(specs)

        def submit(i):
            key, n = specs[i]
            results[i] = post(
                server, "/api/jobs", job_body(key=key, n=n, seed=20 + i)
            )

        threads = [
            threading.Thread(target=submit, args=(i,))
            for i in range(len(specs))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive()

        batch_sizes = []
        for i, (status, resp, _) in enumerate(results):
            assert status == 202, resp
            job = poll_until(server, resp["jobId"])
            assert job["status"] == "done", job
            n = specs[i][1]
            visited = sorted(
                c
                for v in job["message"]["vehicles"]
                for c in v["tour"][1:-1]
            )
            assert visited == list(range(1, n)), (i, job)
            batch_sizes.append(job["batchSize"])
        # the burst queued while the worker was busy, so at least one
        # same-shape pair must have merged into one launch
        assert max(batch_sizes) >= 2, batch_sizes


class TestBackpressure:
    @pytest.fixture()
    def tiny_queue(self):
        import os

        jobs_mod.shutdown_scheduler()
        os.environ["VRPMS_SCHED_QUEUE"] = "2"
        yield
        os.environ.pop("VRPMS_SCHED_QUEUE", None)
        jobs_mod.shutdown_scheduler()

    def test_queue_full_is_429_with_retry_after(self, server, tiny_queue):
        # worker busy on a ~3s blocker, 2-slot queue filled, then both
        # the async submit and the sync endpoint must shed with 429 +
        # Retry-After immediately (not hang behind the queue)
        status, resp, _ = post(
            server,
            "/api/jobs",
            job_body(iterationCount=500_000, populationSize=64,
                     timeLimit=3, seed=30),
        )
        assert status == 202, resp
        time.sleep(0.3)  # blocker picked up; queue now empty
        for i in (1, 2):
            status, resp, _ = post(
                server, "/api/jobs",
                job_body(seed=30 + i, iterationCount=100 + i),
            )
            assert status == 202, resp
        t0 = time.monotonic()
        status, resp, headers = post(
            server, "/api/jobs", job_body(seed=40)
        )
        assert status == 429, resp
        assert time.monotonic() - t0 < 5.0  # shed, not queued-and-hung
        assert resp["success"] is False
        assert resp["errors"][0]["what"] == "Too busy"
        assert int(headers["Retry-After"]) >= 1
        # the synchronous endpoints shed identically
        sync_body = job_body(seed=41)
        del sync_body["problem"], sync_body["algorithm"]
        status, resp, headers = post(server, "/api/vrp/sa", sync_body)
        assert status == 429, resp
        assert "Retry-After" in headers


class TestDrainOnShutdown:
    def test_shutdown_fails_queued_jobs_cleanly(self, server):
        status, resp, _ = post(
            server,
            "/api/jobs",
            job_body(iterationCount=500_000, populationSize=64,
                     timeLimit=2, seed=50),
        )
        assert status == 202, resp
        time.sleep(0.3)
        queued = []
        for i in range(2):
            status, resp, _ = post(
                server, "/api/jobs", job_body(seed=60 + i)
            )
            assert status == 202, resp
            queued.append(resp["jobId"])
        drained = jobs_mod.shutdown_scheduler()
        assert drained >= 1
        for job_id in queued:
            job = poll_until(server, job_id, timeout=10.0)
            assert job["status"] == "failed", job
            assert job["errors"][0]["what"] == "Service unavailable"
        # the NEXT request lazily builds a fresh scheduler and serves
        status, resp, _ = post(server, "/api/jobs", job_body(seed=70))
        assert status == 202, resp
        assert poll_until(server, resp["jobId"])["status"] == "done"
