"""Golden tests: exact brute force vs itertools, local search behavior."""

import itertools

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from vrpms_tpu.core import make_instance
from vrpms_tpu.core.cost import CostWeights, evaluate_giant, total_cost
from vrpms_tpu.core.encoding import is_valid_giant, random_giant
from vrpms_tpu.solvers import solve_tsp_bf, solve_vrp_bf, solve_nn_2opt, local_search
from vrpms_tpu.solvers.bf import MAX_BF_CUSTOMERS
from vrpms_tpu.solvers.local_search import nearest_neighbor_perm
from tests.oracle import route_list_cost
from tests.test_core_cost import random_instance


def python_tsp_optimum(d):
    n = d.shape[0] - 1
    best = np.inf
    for perm in itertools.permutations(range(1, n + 1)):
        path = [0, *perm, 0]
        best = min(best, sum(d[a, b] for a, b in zip(path[:-1], path[1:])))
    return best


def python_vrp_optimum(d, demands, q, v):
    n = d.shape[0] - 1
    best = np.inf
    for perm in itertools.permutations(range(1, n + 1)):
        for n_cuts in range(0, v):
            for cuts in itertools.combinations(range(1, n), n_cuts):
                bounds = [0, *cuts, n]
                routes = [list(perm[a:b]) for a, b in zip(bounds[:-1], bounds[1:])]
                if any(sum(demands[c] for c in r) > q for r in routes):
                    continue
                cost = 0.0
                for r in routes:
                    path = [0, *r, 0]
                    cost += sum(d[a, b] for a, b in zip(path[:-1], path[1:]))
                best = min(best, cost)
    return best


class TestBruteForce:
    def test_perm_decode_matches_host_at_wide_batch(self):
        # Regression: the original bool-mask formulation of the Lehmer
        # decode (argmax over cumsum(~used) ranks + scatter) was
        # MISCOMPILED by XLA:TPU at wide vmap batches — 85% of rows came
        # back with repeated customers at batch 8192 on v5e, silently
        # breaking the BF oracle on hardware while CPU stayed correct.
        # The gather/roll decode must match the host Lehmer walk exactly,
        # at exactly the batch widths the enumeration uses. (bench.py
        # re-asserts validity on the real device every round.)
        import math

        from vrpms_tpu.solvers.bf import _perm_from_index

        n = 8
        idxs = jnp.arange(8192, dtype=jnp.int32)
        perms = np.asarray(
            jax.jit(jax.vmap(lambda i: _perm_from_index(i, n)))(idxs)
        )

        def host(i):
            avail = list(range(n))
            out = []
            for k in range(n):
                f = math.factorial(n - 1 - k)
                out.append(avail.pop(i // f))
                i %= f
            return out

        for i in (0, 1, 2879, 2880, 5039, 5040, 8191):
            assert list(perms[i]) == host(i), i
        assert all(sorted(r) == list(range(n)) for r in perms)

    def test_tsp_matches_itertools(self, rng):
        n = 7
        d = rng.uniform(1, 50, size=(n, n))
        np.fill_diagonal(d, 0)
        inst = make_instance(d, n_vehicles=1)
        res = solve_tsp_bf(inst)
        assert np.isclose(float(res.cost), python_tsp_optimum(d), rtol=1e-5)
        assert is_valid_giant(res.giant, n - 1, 1)
        assert int(res.evals) == 720

    def test_tsp_asymmetric(self, rng):
        n = 6
        d = rng.uniform(1, 50, size=(n, n))  # asymmetric on purpose
        np.fill_diagonal(d, 0)
        inst = make_instance(d, n_vehicles=1)
        res = solve_tsp_bf(inst)
        assert np.isclose(float(res.cost), python_tsp_optimum(d), rtol=1e-5)

    def test_vrp_matches_itertools(self, rng):
        n = 7
        d = rng.uniform(1, 50, size=(n, n))
        np.fill_diagonal(d, 0)
        demands = np.array([0, 3, 4, 2, 5, 3, 4], dtype=float)
        inst = make_instance(d, demands=demands, capacities=[9, 9, 9])
        res = solve_vrp_bf(inst)
        want = python_vrp_optimum(d, demands, 9.0, 3)
        assert np.isclose(float(res.breakdown.distance), want, rtol=1e-5)
        assert is_valid_giant(res.giant, n - 1, 3)
        assert float(res.breakdown.cap_excess) == 0.0

    def test_deadline_none_and_generous_agree(self, rng):
        # the chunked deadline path composes to exactly the single-shot
        # reduction when the deadline is never hit
        n = 8
        d = rng.uniform(1, 50, size=(n, n))
        np.fill_diagonal(d, 0)
        demands = [0] + [1] * (n - 1)
        inst = make_instance(d, demands=demands, capacities=[4, 4, 4])
        exact = solve_vrp_bf(inst)
        timed = solve_vrp_bf(inst, deadline_s=60.0)
        assert np.isclose(float(timed.cost), float(exact.cost), rtol=1e-6)
        assert int(timed.evals) == int(exact.evals) == 5040
        t_exact = solve_tsp_bf(make_instance(d, n_vehicles=1))
        t_timed = solve_tsp_bf(make_instance(d, n_vehicles=1), deadline_s=60.0)
        assert np.isclose(float(t_timed.cost), float(t_exact.cost), rtol=1e-6)

    def test_deadline_zero_truncates_but_returns_valid(self, rng):
        # timeLimit 0 = "stop as soon as possible": exactly one ~262k-
        # order chunk of the 10-customer space (3.6M orders) is scored,
        # and the best-so-far is still a valid, finitely-priced solution
        n = 11
        d = rng.uniform(1, 50, size=(n, n))
        np.fill_diagonal(d, 0)
        demands = [0] + [1] * (n - 1)
        inst = make_instance(d, demands=demands, capacities=[5, 5, 5])
        res = solve_vrp_bf(inst, deadline_s=0.0)
        import math

        assert int(res.evals) < math.factorial(10)
        assert int(res.evals) >= (1 << 13) * 32  # at least one chunk ran
        assert np.isfinite(float(res.cost))
        assert is_valid_giant(res.giant, n - 1, inst.n_vehicles)

    def test_rejects_large(self, rng):
        inst = random_instance(rng, n=MAX_BF_CUSTOMERS + 2, v=1)
        with pytest.raises(ValueError, match="exceeds"):
            solve_tsp_bf(inst)

    def test_vrp_tw_runs_and_beats_random(self, rng):
        inst = random_instance(rng, n=6, v=2, tw=True)
        res = solve_vrp_bf(inst)
        w = CostWeights.make()
        for seed in range(20):
            g = random_giant(jax.random.key(seed), 5, 2)
            assert float(res.cost) <= float(total_cost(evaluate_giant(g, inst), w)) + 1e-3


class TestLocalSearch:
    def test_improves_and_valid(self, rng):
        inst = random_instance(rng, n=12, v=3)
        g0 = random_giant(jax.random.key(3), 11, 3)
        w = CostWeights.make()
        c0 = float(total_cost(evaluate_giant(g0, inst), w))
        res = local_search(g0, inst, w)
        assert float(res.cost) <= c0
        assert is_valid_giant(res.giant, 11, 3)
        assert int(res.evals) > 0

    def test_local_search_reaches_bf_on_tiny_tsp(self, rng):
        # On very small instances steepest descent from NN often hits the
        # optimum; at minimum it must be within a loose factor.
        n = 7
        d = rng.uniform(1, 50, size=(n, n))
        d = (d + d.T) / 2
        np.fill_diagonal(d, 0)
        inst = make_instance(d, n_vehicles=1)
        opt = float(solve_tsp_bf(inst).cost)
        got = float(solve_nn_2opt(inst).cost)
        assert got <= opt * 1.2 + 1e-3

    def test_nn_2opt_tsp50(self, rng):
        pts = rng.uniform(0, 100, size=(51, 2))
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        inst = make_instance(d, n_vehicles=1)
        order = nearest_neighbor_perm(inst)
        assert sorted(np.asarray(order).tolist()) == list(range(1, 51))
        zero = jnp.zeros(1, dtype=jnp.int32)
        nn_giant = jnp.concatenate([zero, order, zero])
        w = CostWeights.make()
        nn_cost = float(total_cost(evaluate_giant(nn_giant, inst), w))
        res = solve_nn_2opt(inst, w)
        assert float(res.cost) < nn_cost  # 2-opt must strictly help on random points
        assert is_valid_giant(res.giant, 50, 1)

    def test_nn_2opt_vrp(self, rng):
        inst = random_instance(rng, n=15, v=4)
        res = solve_nn_2opt(inst)
        assert is_valid_giant(res.giant, 14, 4)
