"""Warm-start checkpointing + stats/profile options (framework extensions).

The reference has no computation checkpointing (SURVEY.md §5); this
covers the solutionName-keyed warm-start seam end-to-end over HTTP, the
id-remapping under dynamic re-solve (ignored/completed), and the
includeStats attachment.
"""

import numpy as np
import jax.numpy as jnp
import pytest

import store.memory as mem
from service.solve import _warm_perm
from tests.test_service import (  # noqa: F401  (fixtures)
    needs_shard_map,
    post,
    seeded,
    server,
)


ALICE = "alice@example.com"  # registered for "tok-alice" by the seeded fixture


def vrp_body(**over):
    body = {
        "auth": "tok-alice",  # checkpoints are owner-scoped like saves
        "solutionName": "ws-sol",
        "solutionDescription": "d",
        "locationsKey": "locs1",
        "durationsKey": "durs1",
        "capacities": [6, 6, 6],
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "iterationCount": 300,
        "populationSize": 16,
        "includeStats": True,
    }
    body.update(over)
    return body


class TestWarmPerm:
    def test_preserves_order_and_appends_new(self):
        state = {"problem": "vrp", "routes": [[5, 3], [9]]}
        # active ids: depot 0, then customers 3, 5, 7 (9 was completed)
        got = _warm_perm(state, [0, 3, 5, 7], "vrp")
        assert got is not None
        # 5 -> pos 2, 3 -> pos 1, 9 dropped, new customer 7 appended
        assert np.asarray(got).tolist() == [2, 1, 3]

    def test_rejects_cross_problem_and_empty(self):
        assert _warm_perm({"problem": "tsp", "routes": [[1]]}, [0, 1], "vrp") is None
        assert _warm_perm(None, [0, 1], "vrp") is None
        assert _warm_perm({"problem": "vrp", "routes": []}, [0], "vrp") is None


class TestWarmStartHTTP:
    def test_checkpoint_saved_and_reused(self, server):
        status, first = post(server, "/api/vrp/sa", vrp_body())
        assert status == 200 and first["success"]
        assert first["message"]["stats"]["warmStart"] is False
        ws = mem._tables["warmstarts"].get((ALICE, "ws-sol"))
        assert ws is not None and ws["state"]["problem"] == "vrp"
        saved_routes = ws["state"]["routes"]
        assert sorted(c for r in saved_routes for c in r) == [1, 2, 3, 4, 5, 6]

        status, second = post(server, "/api/vrp/sa", vrp_body(warmStart=True))
        assert status == 200 and second["success"]
        assert second["message"]["stats"]["warmStart"] is True
        # warm-started solve must not be worse than the checkpointed cost
        assert (
            second["message"]["durationSum"]
            <= ws["state"]["cost"] + 1e-6
        )

    def test_warm_start_survives_dynamic_resolve(self, server):
        status, _ = post(server, "/api/vrp/sa", vrp_body())
        assert status == 200
        status, second = post(
            server,
            "/api/vrp/sa",
            vrp_body(warmStart=True, completedCustomers=[2, 5]),
        )
        assert status == 200 and second["success"]
        served = [
            c for v in second["message"]["vehicles"] for c in v["tour"][1:-1]
        ]
        assert sorted(served) == [1, 3, 4, 6]
        assert second["message"]["stats"]["warmStart"] is True

    def test_tsp_checkpoint_roundtrip(self, server):
        body = {
            "auth": "tok-alice",
            "solutionName": "ws-tsp",
            "solutionDescription": "d",
            "locationsKey": "locs1",
            "durationsKey": "durs1",
            "customers": [1, 2, 3, 4],
            "startNode": 0,
            "startTime": 0,
            "includeStats": True,
            "iterationCount": 300,
            "populationSize": 16,
        }
        status, first = post(server, "/api/tsp/sa", body)
        assert status == 200 and first["success"]
        ws = mem._tables["warmstarts"][(ALICE, "ws-tsp")]
        assert ws["state"]["problem"] == "tsp"
        status, second = post(server, "/api/tsp/sa", dict(body, warmStart=True))
        assert status == 200
        assert second["message"]["stats"]["warmStart"] is True
        assert second["message"]["duration"] <= first["message"]["duration"] + 1e-6

    def test_stats_absent_by_default(self, server):
        body = vrp_body()
        body.pop("includeStats")
        status, resp = post(server, "/api/vrp/sa", body)
        assert status == 200
        assert "stats" not in resp["message"]

    def test_checkpoint_keeps_best_so_far(self, server):
        status, first = post(server, "/api/vrp/sa", vrp_body())
        assert status == 200 and first["success"]
        good = mem._tables["warmstarts"][(ALICE, "ws-sol")]["state"]
        # A deliberately bad follow-up solve over the SAME customer set
        # (1 iteration, adversarial seed) must not clobber the checkpoint.
        status, second = post(
            server, "/api/vrp/sa", vrp_body(iterationCount=1, seed=99)
        )
        assert status == 200 and second["success"]
        kept = mem._tables["warmstarts"][(ALICE, "ws-sol")]["state"]
        assert kept["cost"] <= good["cost"] + 1e-9
        # A dynamic re-solve (different active set) always refreshes.
        status, third = post(
            server, "/api/vrp/sa", vrp_body(completedCustomers=[2])
        )
        assert status == 200 and third["success"]
        refreshed = mem._tables["warmstarts"][(ALICE, "ws-sol")]["state"]
        assert sorted(c for r in refreshed["routes"] for c in r) == [1, 3, 4, 5, 6]

    def test_anonymous_requests_do_not_checkpoint(self, server):
        body = vrp_body()
        del body["auth"]
        status, resp = post(server, "/api/vrp/sa", body)
        assert status == 200 and resp["success"]
        assert mem._tables["warmstarts"] == {}
        assert resp["message"]["stats"]["warmStart"] is False

    def test_checkpoints_are_tenant_isolated(self, server):
        mem.register_token("tok-bob", "bob@example.com")
        status, _ = post(server, "/api/vrp/sa", vrp_body())
        assert status == 200
        assert (ALICE, "ws-sol") in mem._tables["warmstarts"]
        # Bob reuses the same solutionName: he must neither read Alice's
        # checkpoint nor overwrite it.
        status, resp = post(
            server, "/api/vrp/sa", vrp_body(auth="tok-bob", seed=7, warmStart=True)
        )
        assert status == 200 and resp["success"]
        assert ("bob@example.com", "ws-sol") in mem._tables["warmstarts"]
        alice_ws = mem._tables["warmstarts"][(ALICE, "ws-sol")]
        assert alice_ws["owner"] == ALICE

    def test_warm_stat_false_for_algorithms_without_seed(self, server):
        # BF is the one remaining algorithm with no warm-start seam
        # (SA/GA seed chains/populations; ACO seeds its colony incumbent)
        status, _ = post(server, "/api/vrp/sa", vrp_body())
        assert status == 200
        status, resp = post(
            server, "/api/vrp/bf", vrp_body(warmStart=True)
        )
        assert status == 200 and resp["success"]
        assert resp["message"]["stats"]["warmStart"] is False
        # ... while ACO now consumes the checkpoint
        status, resp = post(
            server, "/api/vrp/aco", vrp_body(warmStart=True, iterationCount=30)
        )
        assert status == 200 and resp["success"]
        assert resp["message"]["stats"]["warmStart"] is True

    def test_ga_warm_start(self, server):
        status, _ = post(server, "/api/vrp/sa", vrp_body())
        assert status == 200
        status, resp = post(
            server,
            "/api/vrp/ga",
            vrp_body(
                warmStart=True,
                multiThreaded=False,
                randomPermutationCount=16,
                iterationCount=50,
            ),
        )
        assert status == 200 and resp["success"]
        assert resp["message"]["stats"]["warmStart"] is True

    @needs_shard_map
    def test_sa_islands_consume_warm_start(self, server):
        # round 3 (VERDICT r2 item 8): islands + warmStart no longer
        # silently drops the checkpoint for SA — the island chains start
        # from perturbed checkpoint clones and never regress below it
        status, _ = post(server, "/api/vrp/sa", vrp_body())
        assert status == 200
        chk = mem._tables["warmstarts"][(ALICE, "ws-sol")]["state"]["cost"]
        status, resp = post(
            server,
            "/api/vrp/sa",
            vrp_body(warmStart=True, islands=4, iterationCount=40,
                     includeStats=True),
        )
        assert status == 200 and resp["success"]
        assert resp["message"]["stats"]["warmStart"] is True
        assert resp["message"]["stats"]["islands"] == 4
        assert resp["message"]["durationSum"] <= chk + 1e-6

    @needs_shard_map
    def test_ga_islands_consume_warm_start(self, server):
        status, _ = post(server, "/api/vrp/sa", vrp_body())
        assert status == 200
        chk = mem._tables["warmstarts"][(ALICE, "ws-sol")]["state"]["cost"]
        status, resp = post(
            server,
            "/api/vrp/ga",
            vrp_body(warmStart=True, islands=4, iterationCount=30,
                     randomPermutationCount=32, multiThreaded=False,
                     includeStats=True),
        )
        assert status == 200 and resp["success"]
        assert resp["message"]["stats"]["warmStart"] is True
        # GA fitness prices the greedy split of the checkpoint order,
        # which upper-bounds the checkpoint cost — same floor guarantee
        assert resp["message"]["durationSum"] <= chk * 1.0 + 1e-6

    def test_warm_resolve_never_regresses_below_checkpoint(self, server):
        status, first = post(server, "/api/vrp/sa", vrp_body())
        assert status == 200
        chk = mem._tables["warmstarts"][(ALICE, "ws-sol")]["state"]["cost"]
        # a tiny-budget warm re-solve must still return >= checkpoint
        # quality (the exact checkpoint rides along as clone 0)
        status, small = post(
            server, "/api/vrp/sa", vrp_body(warmStart=True, iterationCount=2)
        )
        assert status == 200 and small["success"]
        assert small["message"]["durationSum"] <= chk + 1e-6
