"""Content-addressed solution cache (ISSUE 6).

Covers the cache contract end-to-end over real HTTP plus the unit
seams: exact hits serve byte-identical responses without solving, near
hits repair the cached giant tour to exactly the requested customer
set and never lose to a cold start at equal budget, the legacy
warmStart option rides the same family index, tenants never share
entries, the in-memory tier is LRU-bounded with an eviction counter,
and `VRPMS_CACHE=off` restores the pre-cache responses bit for bit.
"""

import json
import os

import numpy as np
import pytest

import store.memory as mem
from service import cache as solution_cache
from service import obs
from vrpms_tpu.core import make_instance, tiers
from tests.test_service import (  # noqa: F401  (fixtures)
    get,
    post,
    seeded,
    server,
    vrp_body,
    tsp_body,
)


@pytest.fixture(autouse=True)
def cache_env():
    """Restore the cache knobs after each test (they are read per call)."""
    keys = ("VRPMS_CACHE", "VRPMS_CACHE_NEAR")
    saved = {k: os.environ.get(k) for k in keys}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def served_customers(msg):
    return sorted(c for v in msg["vehicles"] for c in v["tour"][1:-1])


def strip_hit(msg):
    return {k: v for k, v in msg.items() if k != "cacheHit"}


# ---------------------------------------------------------------------------
# Unit: the fingerprint is a content address
# ---------------------------------------------------------------------------


class TestFingerprint:
    def _inst(self, d, caps=(6, 6)):
        return make_instance(
            np.asarray(d), demands=[0, 2, 2, 2], capacities=list(caps)
        )

    def test_equal_content_equal_hash(self, rng):
        d = rng.uniform(1, 10, size=(4, 4))
        a = tiers.fingerprint(self._inst(d))
        b = tiers.fingerprint(self._inst(d.copy()))
        assert a == b
        assert len(a) == 64  # sha256 hex

    def test_any_tensor_change_changes_hash(self, rng):
        d = rng.uniform(1, 10, size=(4, 4))
        base = tiers.fingerprint(self._inst(d))
        d2 = d.copy()
        d2[1, 2] += 0.5
        assert tiers.fingerprint(self._inst(d2)) != base

    def test_fleet_change_changes_hash(self, rng):
        d = rng.uniform(1, 10, size=(4, 4))
        assert tiers.fingerprint(self._inst(d, caps=(6, 6))) != tiers.fingerprint(
            self._inst(d, caps=(6, 6, 6))
        )

    def test_padding_canonicalizes(self, rng):
        # the cache-critical property: the PADDED instance hashes equal
        # no matter how the request spelled the same content
        d = rng.uniform(1, 10, size=(4, 4))
        p1 = tiers.pad_instance(self._inst(d))
        p2 = tiers.pad_instance(self._inst(np.asarray(d.tolist())))
        assert tiers.fingerprint(p1) == tiers.fingerprint(p2)


# ---------------------------------------------------------------------------
# Unit: the in-memory tier is LRU-bounded
# ---------------------------------------------------------------------------


class TestLRUBound:
    def test_cap_evicts_least_recently_used(self):
        os.environ["VRPMS_CACHE"] = "2"
        mem.reset()
        db = mem.InMemoryDatabaseVRP(None)
        before = obs.CACHE_EVICTIONS.value
        db.put_cached_solution("k1", "famA", {"cost": 1.0})
        db.put_cached_solution("k2", "famB", {"cost": 2.0})
        # USE k1 (the keyed read the lookup path issues for hits and
        # hydrated seeds): k1 becomes most-recently-used
        assert db.get_cached_solution("k1")["key"] == "k1"
        db.put_cached_solution("k3", "famC", {"cost": 3.0})
        # k2 (least recently used) was evicted, k1 survived
        assert db.get_cache_family("famB") == []
        assert [r["key"] for r in db.get_cache_family("famA")] == ["k1"]
        assert obs.CACHE_EVICTIONS.value == before + 1

    def test_family_scan_does_not_refresh_recency(self):
        # scanning is not using: a big family's misses must not evict
        # other tenants' hot rows — only the hydrating keyed read counts
        os.environ["VRPMS_CACHE"] = "2"
        mem.reset()
        db = mem.InMemoryDatabaseVRP(None)
        db.put_cached_solution("k1", "famA", {"cost": 1.0})
        db.put_cached_solution("k2", "famB", {"cost": 2.0})
        assert [r["key"] for r in db.get_cache_family("famA")] == ["k1"]
        db.put_cached_solution("k3", "famC", {"cost": 3.0})
        # the famA scan did NOT refresh k1: k1 was still the LRU entry
        assert db.get_cache_family("famA") == []
        assert [r["key"] for r in db.get_cache_family("famB")] == ["k2"]

    def test_rewrite_refreshes_not_evicts(self):
        os.environ["VRPMS_CACHE"] = "2"
        mem.reset()
        db = mem.InMemoryDatabaseVRP(None)
        db.put_cached_solution("k1", "famA", {"cost": 1.0})
        db.put_cached_solution("k1", "famA", {"cost": 1.5})  # same key
        db.put_cached_solution("k2", "famB", {"cost": 2.0})
        assert [r["entry"]["cost"] for r in db.get_cache_family("famA")] == [1.5]
        assert [r["key"] for r in db.get_cache_family("famB")] == ["k2"]


# ---------------------------------------------------------------------------
# HTTP: exact hits
# ---------------------------------------------------------------------------


class TestExactHit:
    def test_byte_identical_and_counted(self, server):
        b = vrp_body(iterationCount=150)
        avoided0 = obs.CACHE_SOLVES_AVOIDED.value
        s1, r1 = post(server, "/api/vrp/sa", b)
        assert s1 == 200 and r1["message"]["cacheHit"] is False
        s2, r2 = post(server, "/api/vrp/sa", b)
        assert s2 == 200 and r2["message"]["cacheHit"] is True
        assert json.dumps(strip_hit(r1["message"]), sort_keys=True) == json.dumps(
            strip_hit(r2["message"]), sort_keys=True
        )
        assert obs.CACHE_SOLVES_AVOIDED.value == avoided0 + 1

    def test_certificate_served_from_cache(self, server):
        # the BF proof certificate is part of the cached response
        b = vrp_body()
        s1, r1 = post(server, "/api/vrp/bf", b)
        s2, r2 = post(server, "/api/vrp/bf", b)
        assert s2 == 200 and r2["message"]["cacheHit"] is True
        assert r2["message"]["exact"] == r1["message"]["exact"]

    def test_tsp_exact_hit(self, server):
        b = tsp_body(iterationCount=150)
        post(server, "/api/tsp/sa", b)
        s2, r2 = post(server, "/api/tsp/sa", b)
        assert s2 == 200 and r2["message"]["cacheHit"] is True

    def test_option_change_is_a_miss(self, server):
        b = vrp_body(iterationCount=150)
        post(server, "/api/vrp/sa", b)
        for variant in (
            vrp_body(iterationCount=150, seed=2),
            vrp_body(iterationCount=151),
            vrp_body(iterationCount=150, completedCustomers=[2]),
        ):
            _, r = post(server, "/api/vrp/sa", variant)
            assert r["message"]["cacheHit"] is False

    def test_stats_requests_solve_anyway(self, server):
        # includeStats telemetry must be real: the exact entry is found
        # but NOT served — and not seeded either, so the solve stays
        # byte-identical to its plain twin (same seed, same program)
        b = vrp_body(iterationCount=150)
        _, plain = post(server, "/api/vrp/sa", b)
        _, r = post(server, "/api/vrp/sa", dict(b, includeStats=True))
        assert r["message"]["cacheHit"] is False
        assert r["message"]["stats"]["cache"]["lookup"] == "exact"
        assert r["message"]["stats"]["cache"]["seeded"] is False
        stripped = strip_hit(r["message"])
        stripped.pop("stats")
        assert stripped == strip_hit(plain["message"])

    def test_async_job_born_done_on_hit(self, server):
        b = dict(vrp_body(iterationCount=150), problem="vrp", algorithm="sa")
        s, r = post(server, "/api/jobs", b)
        assert s == 202
        import time

        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            _, poll = post_get(server, f"/api/jobs/{r['jobId']}")
            if poll["job"]["status"] in ("done", "failed"):
                break
            time.sleep(0.05)
        assert poll["job"]["status"] == "done"
        # identical submit: the job is born done from the cache — it
        # never touches the admission queue or the solver
        s2, r2 = post(server, "/api/jobs", b)
        assert s2 == 202
        _, poll2 = post_get(server, f"/api/jobs/{r2['jobId']}")
        assert poll2["job"]["status"] == "done"
        assert poll2["job"]["message"]["cacheHit"] is True

    def test_trivial_response_carries_cache_hit_key(self, server):
        # zero-customer requests short-circuit before the cache lookup
        # but keep the contract key uniform: present (false) when the
        # cache is on, absent when it is off
        b = vrp_body(completedCustomers=[1, 2, 3, 4, 5, 6])
        s, r = post(server, "/api/vrp/sa", b)
        assert s == 200 and r["message"]["vehicles"] == []
        assert r["message"]["cacheHit"] is False
        os.environ["VRPMS_CACHE"] = "off"
        s, r = post(server, "/api/vrp/sa", b)
        assert s == 200 and "cacheHit" not in r["message"]

    def test_metrics_expose_cache_series(self, server):
        b = vrp_body(iterationCount=150)
        post(server, "/api/vrp/sa", b)
        post(server, "/api/vrp/sa", b)
        _, text = get(server, "/metrics")
        assert 'vrpms_cache_lookups_total{outcome="exact"}' in text
        assert "vrpms_cache_solves_avoided_total" in text
        assert "vrpms_cache_evictions_total" in text


def post_get(base, path):
    import urllib.request

    with urllib.request.urlopen(base + path) as resp:
        return resp.status, json.loads(resp.read())


# ---------------------------------------------------------------------------
# HTTP: tenant isolation + the off switch
# ---------------------------------------------------------------------------


class TestTenantIsolation:
    def test_auth_scopes_never_share_entries(self, server):
        mem.register_token("tok-bob", "bob@example.com")
        b = vrp_body(iterationCount=150, auth="tok-alice")
        post(server, "/api/vrp/sa", b)
        _, hit = post(server, "/api/vrp/sa", b)
        assert hit["message"]["cacheHit"] is True
        # same body, different tenant: must solve, not serve alice's row
        _, bob = post(server, "/api/vrp/sa", dict(b, auth="tok-bob"))
        assert bob["message"]["cacheHit"] is False
        # anonymous scope is its own tenant too
        _, anon = post(server, "/api/vrp/sa", vrp_body(iterationCount=150))
        assert anon["message"]["cacheHit"] is False


class TestCacheOff:
    def test_responses_byte_identical_to_pre_cache(self, server):
        os.environ["VRPMS_CACHE"] = "off"
        b = vrp_body(iterationCount=150)
        s1, r1 = post(server, "/api/vrp/sa", b)
        s2, r2 = post(server, "/api/vrp/sa", b)
        assert s1 == s2 == 200
        # no cache annotations, no cache rows, every request solves
        assert "cacheHit" not in r1["message"]
        assert "cacheHit" not in r2["message"]
        assert mem._tables["solution_cache"] == {}
        # deterministic solver, same seed: the two solves agree, which
        # is exactly the seed-era response for this body
        assert json.dumps(r1["message"], sort_keys=True) == json.dumps(
            r2["message"], sort_keys=True
        )

    def test_off_still_serves_legacy_warmstart(self, server):
        os.environ["VRPMS_CACHE"] = "off"
        b = vrp_body(iterationCount=150, auth="tok-alice", includeStats=True)
        post(server, "/api/vrp/sa", b)
        _, r = post(server, "/api/vrp/sa", dict(b, warmStart=True))
        assert r["message"]["stats"]["warmStart"] is True
        assert "cache" not in r["message"]["stats"]


# ---------------------------------------------------------------------------
# HTTP: near hits repair + seed
# ---------------------------------------------------------------------------


class TestNearHit:
    def test_strip_preserves_customer_set(self, server):
        post(server, "/api/vrp/sa", vrp_body(iterationCount=150))
        _, r = post(
            server,
            "/api/vrp/sa",
            vrp_body(iterationCount=150, completedCustomers=[2], includeStats=True),
        )
        assert served_customers(r["message"]) == [1, 3, 4, 5, 6]
        assert r["message"]["stats"]["cache"]["lookup"] == "near"
        assert r["message"]["stats"]["cache"]["seeded"] is True

    def test_insert_preserves_customer_set(self, server):
        post(server, "/api/vrp/sa", vrp_body(iterationCount=150, completedCustomers=[2, 5]))
        _, r = post(
            server,
            "/api/vrp/sa",
            vrp_body(iterationCount=150, includeStats=True),
        )
        # the cached 4-customer tour greedy-inserts 2 and 5 back: the
        # served set is exactly the requested one, nothing lost or kept
        assert served_customers(r["message"]) == [1, 2, 3, 4, 5, 6]
        assert r["message"]["stats"]["cache"]["lookup"] == "near"

    def test_distance_cap_and_disable(self, server):
        post(server, "/api/vrp/sa", vrp_body(iterationCount=150))
        os.environ["VRPMS_CACHE_NEAR"] = "1"
        _, r = post(
            server,
            "/api/vrp/sa",
            vrp_body(iterationCount=150, completedCustomers=[2, 3], includeStats=True),
        )
        assert r["message"]["stats"]["cache"]["lookup"] == "miss"
        os.environ["VRPMS_CACHE_NEAR"] = "0"
        _, r = post(
            server,
            "/api/vrp/sa",
            vrp_body(iterationCount=150, completedCustomers=[2], includeStats=True),
        )
        assert r["message"]["stats"]["cache"]["lookup"] == "miss"

    def test_never_loses_to_cold_start_at_equal_budget(self, server):
        # acceptance: warm-start-from-similar matches or beats the cold
        # NN construction at the SAME iteration budget and seed
        post(server, "/api/vrp/sa", vrp_body(iterationCount=500))
        near = vrp_body(iterationCount=40, seed=3, completedCustomers=[6])
        _, warm = post(server, "/api/vrp/sa", near)
        assert warm["message"]["cacheHit"] is False  # seeded, not served
        os.environ["VRPMS_CACHE"] = "off"
        _, cold = post(server, "/api/vrp/sa", near)
        assert (
            warm["message"]["durationSum"]
            <= cold["message"]["durationSum"] + 1e-6
        )


# ---------------------------------------------------------------------------
# HTTP: one warm-start code path through the family index
# ---------------------------------------------------------------------------


class TestWarmStartViaIndex:
    def test_explicit_warmstart_served_from_index(self, server):
        b = vrp_body(iterationCount=200, auth="tok-alice")
        post(server, "/api/vrp/sa", b)
        # kill the legacy checkpoint row: the ONLY remaining source is
        # the fingerprint/family index — the keyed read must still warm
        mem._tables["warmstarts"].clear()
        warm0 = obs.CACHE_LOOKUPS.labels(outcome="warm").value
        _, r = post(
            server, "/api/vrp/sa", dict(b, warmStart=True, includeStats=True)
        )
        assert r["message"]["stats"]["warmStart"] is True
        assert r["message"]["stats"]["cache"]["lookup"] == "warm"
        assert obs.CACHE_LOOKUPS.labels(outcome="warm").value == warm0 + 1

    def test_cold_index_falls_back_to_checkpoint(self, server):
        b = vrp_body(iterationCount=200, auth="tok-alice")
        post(server, "/api/vrp/sa", b)
        # inverse: evicted/cold family index, surviving checkpoint row
        mem._tables["solution_cache"].clear()
        _, r = post(
            server, "/api/vrp/sa", dict(b, warmStart=True, includeStats=True)
        )
        assert r["message"]["stats"]["warmStart"] is True


# ---------------------------------------------------------------------------
# Unit: repair over the separator encoding
# ---------------------------------------------------------------------------


class TestRepairPerm:
    class _Prep:
        def __init__(self, ids, durations):
            self.orig_ids = ids
            self.inst = type("I", (), {"durations": durations})()

    def test_strip_keeps_relative_order(self):
        # cached routes over original ids 10,20,30,40; request drops 30
        d = np.ones((1, 5, 5), dtype=np.float32)
        prep = self._Prep([0, 10, 20, 40], d)
        got = solution_cache._repair_perm(prep, [[40, 30], [20, 10]])
        assert np.asarray(got).tolist() == [3, 2, 1]

    def test_insert_places_new_customer_cheapest(self):
        # depot (0,0) -> 1 (1,0) -> 2 (2,0); new customer 3 at (2,1)
        # is cheapest appended after 2, not wedged before it
        pts = np.array([[0.0, 0.0], [1.0, 0.0], [2.0, 0.0], [2.0, 1.0]])
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        d = d[None, :, :].astype(np.float32)
        prep = self._Prep([0, 1, 2, 3], d)
        got = solution_cache._repair_perm(prep, [[1, 2]])
        assert np.asarray(got).tolist() == [1, 2, 3]

    def test_nothing_survives_declines_to_seed(self):
        d = np.ones((1, 3, 3), dtype=np.float32)
        prep = self._Prep([0, 7, 8], d)
        assert solution_cache._repair_perm(prep, [[99]]) is None


# ---------------------------------------------------------------------------
# Containment: a cache problem degrades to solving, never to failing
# ---------------------------------------------------------------------------


class TestContainment:
    def test_corrupt_row_degrades_to_solving(self, server):
        # store I/O errors are contained at the seam; a malformed entry
        # DOCUMENT (migration script, truncated jsonb) raises above it
        # and attach() must degrade that to a normal solve, never a 400
        b = vrp_body(iterationCount=150)
        s1, r1 = post(server, "/api/vrp/sa", b)
        assert s1 == 200
        for row in mem._tables["solution_cache"].values():
            row["entry"] = ["not", "a", "document"]
        s2, r2 = post(server, "/api/vrp/sa", b)
        assert s2 == 200, r2
        assert r2["message"]["cacheHit"] is False  # solved for real
        assert json.dumps(strip_hit(r1["message"]), sort_keys=True) == json.dumps(
            strip_hit(r2["message"]), sort_keys=True
        )

    def test_junk_customers_degrade_to_solving(self, server):
        # unhashable members poison the near-hit set arithmetic; the
        # request must fall back to an unseeded solve of the right set
        post(server, "/api/vrp/sa", vrp_body(iterationCount=150))
        for row in mem._tables["solution_cache"].values():
            row["entry"]["customers"] = [["un"], ["hashable"]]
        s, r = post(
            server,
            "/api/vrp/sa",
            vrp_body(iterationCount=150, completedCustomers=[2]),
        )
        assert s == 200, r
        assert r["message"]["cacheHit"] is False
        assert served_customers(r["message"]) == [1, 3, 4, 5, 6]


class TestNonIntegerIds:
    def test_string_ids_cache_and_hit(self, server):
        # the schema doc says int ids but nothing validates; pre-cache
        # the service accepted any id type, so the cache keys must too
        rng = np.random.default_rng(5)
        pts = rng.uniform(0, 100, size=(5, 2))
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        mem.seed_locations(
            "locs_str",
            [
                {"id": f"loc-{i}" if i else "depot", "demand": 2 if i else 0}
                for i in range(5)
            ],
        )
        mem.seed_durations("durs_str", d.tolist())
        b = vrp_body(
            locationsKey="locs_str",
            durationsKey="durs_str",
            capacities=[8, 8],
            startTimes=[0, 0],
            iterationCount=150,
        )
        s1, r1 = post(server, "/api/vrp/sa", b)
        assert s1 == 200, r1
        assert r1["message"]["cacheHit"] is False
        s2, r2 = post(server, "/api/vrp/sa", b)
        assert s2 == 200 and r2["message"]["cacheHit"] is True
        assert json.dumps(strip_hit(r1["message"]), sort_keys=True) == json.dumps(
            strip_hit(r2["message"]), sort_keys=True
        )

    def test_off_flip_skips_write_without_mass_evict(self):
        # VRPMS_CACHE flips to off between a request's attach and its
        # finish: the late write must be skipped, not clamp the cap to
        # 1 and evict every existing entry
        os.environ["VRPMS_CACHE"] = "8"
        mem.reset()
        db = mem.InMemoryDatabaseVRP(None)
        for i in range(4):
            db.put_cached_solution(f"k{i}", "famA", {"cost": float(i)})
        os.environ["VRPMS_CACHE"] = "off"
        before = obs.CACHE_EVICTIONS.value
        db.put_cached_solution("k-late", "famA", {"cost": 9.0})
        assert len(mem._tables["solution_cache"]) == 4
        assert "k-late" not in mem._tables["solution_cache"]
        assert obs.CACHE_EVICTIONS.value == before


class TestSingleDeadline:
    def test_first_failure_disables_cache_for_the_request(self):
        # a hung/failing cache store must cost a request at most ONE
        # call before the instance-level latch sheds the rest — not one
        # deadline per lookup step (exact read, family scan, hydration)
        calls = []

        class _Failing(mem.InMemoryDatabaseVRP):
            def _fetch_cached_solution(self, key):
                calls.append("exact")
                raise RuntimeError("store hang")

            def _fetch_cache_family(self, family):
                calls.append("family")
                raise RuntimeError("store hang")

            def _upsert_cached_solution(self, key, family, entry):
                calls.append("write")
                raise RuntimeError("store hang")

        db = _Failing(None)
        assert db.get_cached_solution("k") is None
        assert db.get_cache_family("fam") == []
        assert db.put_cached_solution("k", "fam", {}) is False
        assert calls == ["exact"]  # only the first call reached the store
        # a fresh instance (the next request) tries again
        assert db.__class__(None).get_cache_family("fam") == []
        assert calls == ["exact", "family"]
