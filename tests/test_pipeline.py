"""Pipelined block dispatch (ISSUE 19): the depth-1 driver's contracts.

Fixed-seed byte-identity between VRPMS_PIPELINE=on and off across
SA/GA/ACO (sink attached and detached), the off-mode launch sequence
pinned to the pre-pipeline serial loop, probe-skip when a rate hint is
known, cancel honored within ≤2 block boundaries, checkpoint capture
cadence still bounded, and the deadline-overshoot property (≤ one
block beyond the serial contract) over a synthetic slow step_block.
"""

import time

import numpy as np
import pytest

from vrpms_tpu.core import make_instance
from vrpms_tpu.obs import progress
from vrpms_tpu.obs.trace import collect_blocks
from vrpms_tpu.solvers import common
from vrpms_tpu.solvers.aco import ACOParams, solve_aco
from vrpms_tpu.solvers.common import run_blocked
from vrpms_tpu.solvers.ga import GAParams, solve_ga
from vrpms_tpu.solvers.sa import SAParams, solve_sa


@pytest.fixture(autouse=True)
def _isolated_rates(tmp_path, monkeypatch):
    """Identity comparisons need BOTH runs to see the same hint state:
    isolate the persistent rate cache and start each test hint-less."""
    monkeypatch.setenv("VRPMS_RATE_CACHE", str(tmp_path / "rates.json"))
    saved = dict(common._SWEEP_RATE)
    loaded = common._RATE_LOADED
    common._SWEEP_RATE.clear()
    common._RATE_LOADED = True  # keep the empty dict; skip the file load
    yield
    common._SWEEP_RATE.clear()
    common._SWEEP_RATE.update(saved)
    common._RATE_LOADED = loaded


def _clear_rates():
    common._SWEEP_RATE.clear()


def _small_cvrp(n=10, v=2, q=14, seed=3):
    rng = np.random.default_rng(seed)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    demands = np.concatenate([[0], rng.uniform(1, 4, size=n - 1)])
    return make_instance(d, demands=demands, capacities=[q] * v)


_SOLVERS = {
    "sa": lambda inst: solve_sa(
        inst, key=0, params=SAParams(n_chains=16, n_iters=900),
        deadline_s=3600.0,
    ),
    "ga": lambda inst: solve_ga(
        inst, key=0, params=GAParams(population=32, generations=80),
        deadline_s=3600.0,
    ),
    "aco": lambda inst: solve_aco(
        inst, key=0, params=ACOParams(n_ants=16, n_iters=48),
        deadline_s=3600.0,
    ),
}


class TestByteIdentity:
    """Fixed-seed results are bit-identical with pipelining on or off —
    the device computation sequence (step_block sizes + offsets) is the
    same in both modes on a generous deadline."""

    @pytest.mark.parametrize("algo", ["sa", "ga", "aco"])
    @pytest.mark.parametrize("with_sink", [False, True])
    def test_on_off_identical(self, monkeypatch, algo, with_sink):
        inst = _small_cvrp()
        results = {}
        for mode in ("on", "off"):
            monkeypatch.setenv("VRPMS_PIPELINE", mode)
            _clear_rates()  # run 1 measures rates; run 2 must not see them
            if with_sink:
                sink = progress.ProgressSink(job_id=f"t-{algo}-{mode}")
                with progress.attach(sink):
                    res = _SOLVERS[algo](inst)
                snap = sink.snapshot()
                assert snap is not None  # the sink saw block cadence
                results[mode] = (res, snap["bestCost"])
            else:
                results[mode] = (_SOLVERS[algo](inst), None)
        on, off = results["on"], results["off"]
        assert np.array_equal(np.asarray(on[0].giant), np.asarray(off[0].giant))
        assert float(on[0].cost) == float(off[0].cost)
        assert float(on[0].evals) == float(off[0].evals)
        if with_sink:
            assert on[1] == off[1]  # published incumbents agree too

    def test_trace_identical_across_modes(self, monkeypatch):
        inst = _small_cvrp()
        costs = {}
        for mode in ("on", "off"):
            monkeypatch.setenv("VRPMS_PIPELINE", mode)
            _clear_rates()
            with collect_blocks() as trace:
                _SOLVERS["sa"](inst)
            assert len(trace.blocks) >= 2
            costs[mode] = [b["bestCost"] for b in trace.blocks]
        # same decomposition, same per-block synced bests — the scalar
        # reduction changes the transfer, never the value
        assert costs["on"] == costs["off"]


def _drive(n_total, block, deadline_s, rate_hint=None, sleep_per_128=0.0,
           incumbent=None, start=1000.0, decay=1.0):
    """Synthetic run_blocked harness: plain host state (a float), a
    step that optionally sleeps proportionally to its size, and a log
    of every launch's (nb, start offset)."""
    launches = []

    def step(state, nb, off):
        launches.append((nb, off))
        if sleep_per_128:
            time.sleep(sleep_per_128 * nb / 128.0)
        return np.float32(state - decay * nb)

    state, done = run_blocked(
        step, np.float32(start), n_total, block, deadline_s,
        lambda s: s, rate_hint=rate_hint, incumbent=incumbent,
    )
    return state, done, launches


class TestLaunchSequence:
    """The decomposition contract both identity and perf rest on."""

    def test_off_mode_matches_pre_pipeline_serial_loop(self, monkeypatch):
        # the serial loop's documented opener: a blind 128 probe when
        # no rate is known, then rate-fitted full blocks — pinned so
        # VRPMS_PIPELINE=off stays byte-identical to the pre-PR driver
        monkeypatch.setenv("VRPMS_PIPELINE", "off")
        _, done, launches = _drive(1024, 512, deadline_s=3600.0)
        assert launches == [(128, 0), (512, 128), (384, 640)]
        assert done == 1024

    def test_pipelined_same_offsets_as_serial(self, monkeypatch):
        monkeypatch.setenv("VRPMS_PIPELINE", "on")
        _, done, launches = _drive(1024, 512, deadline_s=3600.0)
        assert launches == [(128, 0), (512, 128), (384, 640)]
        assert done == 1024

    @pytest.mark.parametrize("mode", ["on", "off"])
    def test_probe_skipped_with_rate_hint(self, monkeypatch, mode):
        # a known same-tier rate lets the FIRST block open at full
        # fitted size instead of the blind 128 probe
        monkeypatch.setenv("VRPMS_PIPELINE", mode)
        _, done, launches = _drive(1024, 512, 3600.0, rate_hint=1e9)
        assert launches[0] == (512, 0)
        assert done == 1024

    @pytest.mark.parametrize("mode", ["on", "off"])
    def test_stale_low_hint_never_stops_unmeasured(self, monkeypatch, mode):
        # regression: a hint that UNDERSTATES the true rate by orders
        # of magnitude (recorded from a compile-dominated run) must not
        # end the solve at a fraction of its budget. The serial loop
        # can never stop without a measurement (it breaks only `if
        # done`); the pipelined driver must drain the in-flight block
        # and re-fit on the MEASURED rate before accepting a hint-based
        # stop verdict.
        monkeypatch.setenv("VRPMS_PIPELINE", mode)
        # claims ~26 it/s against a practically-instant step: the
        # hint-based fit says almost nothing ever fits the clock
        _, done, launches = _drive(4096, 512, 10.0, rate_hint=26.0)
        assert done == 4096, launches

    def test_depth_is_one(self, monkeypatch):
        # launches may lead processed blocks by AT MOST one in-flight
        # block; a sink records processing order, the launch log records
        # dispatch order
        monkeypatch.setenv("VRPMS_PIPELINE", "on")
        events = []

        class _Spy(progress.ProgressSink):
            def record(self, best, iters, evals_per_iter):
                events.append(("proc", iters))
                super().record(best, iters, evals_per_iter)

        def step(state, nb, off):
            events.append(("launch", nb))
            return np.float32(state - nb)

        with progress.attach(_Spy(job_id="depth")):
            run_blocked(
                step, np.float32(100.0), 1024, 128, 3600.0,
                lambda s: s, rate_hint=1e9,
            )
        in_flight = 0
        for kind, _ in events:
            in_flight += 1 if kind == "launch" else -1
            assert 0 <= in_flight <= 2  # the processing block + one launched
        assert in_flight == 0  # every launched block was drained


class TestCancelDeferral:
    @pytest.mark.parametrize("mode,max_extra", [("off", 0), ("on", 1)])
    def test_cancel_within_two_boundaries(self, monkeypatch, mode, max_extra):
        monkeypatch.setenv("VRPMS_PIPELINE", mode)
        cancel_after = 3

        class _CancelAfter(progress.ProgressSink):
            def record(self, best, iters, evals_per_iter):
                super().record(best, iters, evals_per_iter)
                if self._block >= cancel_after:
                    self.cancel()

        sink = _CancelAfter(job_id="cancel")
        with progress.attach(sink):
            _, done, launches = _drive(
                128 * 100, 128, deadline_s=3600.0, rate_hint=1e9,
            )
        # pipelined: at most ONE extra in-flight block past the cancel
        # boundary, and it is drained + counted, never abandoned
        assert cancel_after <= len(launches) <= cancel_after + max_extra
        assert done == sum(nb for nb, _ in launches)
        assert sink.cancel_acknowledged

    @pytest.mark.parametrize("mode", ["on", "off"])
    def test_cancel_before_first_block(self, monkeypatch, mode):
        monkeypatch.setenv("VRPMS_PIPELINE", mode)
        sink = progress.ProgressSink(job_id="pre")
        sink.cancel()
        with progress.attach(sink):
            _, done, launches = _drive(1024, 128, deadline_s=3600.0)
        assert done == 0 and launches == []
        assert sink.cancel_acknowledged


class TestDeadlineOvershoot:
    @pytest.mark.parametrize("block_time,deadline", [(0.05, 0.12), (0.03, 0.1)])
    def test_overshoot_at_most_one_block_beyond_serial(
        self, monkeypatch, block_time, deadline,
    ):
        # serial contract: overshoot ≤ one block; pipelined adds at
        # most the ONE in-flight block (property over a synthetic slow
        # step_block — the sleep stands in for device compute)
        walls = {}
        for mode in ("on", "off"):
            monkeypatch.setenv("VRPMS_PIPELINE", mode)
            t0 = time.monotonic()
            _, done, _ = _drive(
                128 * 1000, 128, deadline, sleep_per_128=block_time,
            )
            walls[mode] = time.monotonic() - t0
            assert done >= 128  # at least one block always runs
        slack = 0.08  # host bookkeeping + scheduler jitter
        assert walls["off"] <= deadline + block_time + slack
        assert walls["on"] <= deadline + 2 * block_time + slack


class _Handle:
    """Minimal checkpoint-capture handle (service.checkpoint._Entry's
    due/offer contract) with a wall-clock cadence."""

    def __init__(self, interval_s):
        self.interval_s = interval_s
        self.last = time.monotonic()
        self.last_seq = 0
        self.offers = []

    def due(self, sink):
        return (
            time.monotonic() - self.last >= self.interval_s
            and sink.seq != self.last_seq
        )

    def offer(self, sink, giant):
        self.last = time.monotonic()
        self.last_seq = sink.seq
        self.offers.append(np.asarray(giant))


class TestCheckpointCadence:
    @pytest.mark.parametrize("mode", ["on", "off"])
    def test_capture_cadence_bounded(self, monkeypatch, mode):
        monkeypatch.setenv("VRPMS_PIPELINE", mode)
        handle = _Handle(interval_s=0.05)
        sink = progress.ProgressSink(job_id="ckpt")
        sink.ckpt = handle
        t0 = time.monotonic()
        with progress.attach(sink):
            _, done, launches = _drive(
                128 * 12, 128, deadline_s=3600.0, rate_hint=1e9,
                sleep_per_128=0.02,
                incumbent=lambda st: np.full(3, st, np.float32),
            )
        wall = time.monotonic() - t0
        assert done == 128 * 12
        # every block improves (the synthetic best strictly decreases),
        # so captures are limited by the handle's cadence alone: at
        # least one, and never more than the interval admits (+1 for
        # the pipelined one-block deferral)
        n = len(handle.offers)
        assert 1 <= n <= wall / handle.interval_s + 2
        # the captured incumbents reflect synced states (values the
        # driver actually produced at some boundary)
        produced = {float(1000.0 - 128 * k) for k in range(1, 13)}
        for inc in handle.offers:
            assert float(inc[0]) in produced


class TestFanoutNeedsArray:
    @pytest.mark.parametrize("mode", ["on", "off"])
    def test_fanout_rows_not_collapsed(self, monkeypatch, mode):
        # the batched fanout must keep the full per-row best array —
        # a scalar min across the batch would leak job A's cost into
        # job B's stream
        monkeypatch.setenv("VRPMS_PIPELINE", mode)
        a = progress.ProgressSink(job_id="a")
        b = progress.ProgressSink(job_id="b")
        fan = progress.ProgressFanout([a, b])

        def step(state, nb, off):
            return state - np.float32(nb) * np.array([1.0, 2.0], np.float32)

        with progress.attach(fan):
            run_blocked(
                step, np.array([1000.0, 2000.0], np.float32),
                128 * 4, 128, 3600.0, lambda s: s, rate_hint=1e9,
            )
        snap_a, snap_b = a.snapshot(), b.snapshot()
        assert snap_a is not None and snap_b is not None
        assert snap_a["bestCost"] == 1000.0 - 4 * 128
        assert snap_b["bestCost"] == 2000.0 - 2 * 4 * 128


class TestScalarRecordPaths:
    def test_sink_and_trace_accept_host_floats(self):
        sink = progress.ProgressSink(job_id="scalar")
        sink.record(12.5, 128, None)
        assert sink.snapshot()["bestCost"] == 12.5
        sink.record(11.0, 128, None)
        assert sink.snapshot()["bestCost"] == 11.0
        with collect_blocks() as trace:
            from vrpms_tpu.obs.trace import active_trace

            active_trace().record(7.25, 64, 2.0)
        assert trace.blocks[0]["bestCost"] == 7.25
        assert trace.blocks[0]["evals"] == 128
