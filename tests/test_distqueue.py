"""Distributed job queue tests: ring determinism, lease semantics,
tier-affinity claiming, exactly-once reclaim under crashes and store
faults, and the HTTP surface with VRPMS_QUEUE=store.

Layers:

  * TestRing — consistent-hash units: owner/arcs agreement, full slot
    coverage, bounded movement on membership change;
  * TestMemoryQueueStore — the JobQueueStore contract on the shared
    in-memory backend: exclusive leases, conditional renew/ack/nack,
    exactly-once expiry reclaim with the attempt ceiling;
  * TestReplicaRouting — stub-runner replicas: hash-routed claims land
    on ring owners, off-arc work is stolen only when the own arc is
    empty;
  * TestReplicaChaos — kill a replica mid-flight: peers reclaim its
    leases exactly once, a twice-crashed entry dies clean, and claims
    keep working under a VRPMS_STORE=faulty fault plan;
  * TestCrossReplicaChaos (slow) — the ISSUE-9 acceptance gate with
    REAL solves through the service materialize path: a mixed-tier
    trace across two in-process replicas sharing one memory-backed
    queue, one replica killed mid-flight, every job `done` exactly
    once with trace continuity (same traceId, attempt=2);
  * TestServiceDistHTTP (slow) — the HTTP surface end to end under
    VRPMS_QUEUE=store, readiness ring reporting, shared-depth 429s,
    and the default-path-untouched guard.
"""

import json
import threading
import time
import urllib.error
import urllib.request
import uuid

import numpy as np
import pytest

import store
import store.memory as mem
from store.base import Q_QUEUED
from store.faulty import reset_faults
from vrpms_tpu.sched import Job, Replica, Scheduler
from vrpms_tpu.sched.ring import SLOTS, HashRing, slot


@pytest.fixture(autouse=True)
def clean_store(monkeypatch):
    monkeypatch.setenv("VRPMS_STORE", "memory")
    monkeypatch.delenv("VRPMS_QUEUE", raising=False)
    mem.reset()
    reset_faults()
    yield
    mem.reset()


# ---------------------------------------------------------------------------
# Ring
# ---------------------------------------------------------------------------


class TestRing:
    def test_owner_and_arcs_agree_everywhere(self):
        ring = HashRing(["alpha", "beta", "gamma"], vnodes=16)
        rng = np.random.default_rng(0)
        for s in rng.integers(0, SLOTS, size=500):
            owner = ring.owner(int(s))
            assert any(
                lo <= s < hi for lo, hi in ring.arcs(owner)
            ), (s, owner)
            for m in ring.members:
                if m != owner:
                    assert not any(
                        lo <= s < hi for lo, hi in ring.arcs(m)
                    )

    def test_full_coverage_no_overlap(self):
        ring = HashRing(["a", "b"], vnodes=32)
        covered = sum(
            hi - lo for m in ring.members for lo, hi in ring.arcs(m)
        )
        assert covered == SLOTS

    def test_deterministic_across_instances(self):
        a = HashRing(["r1", "r2", "r3"])
        b = HashRing(["r3", "r1", "r2"])  # order must not matter
        for s in (0, 7, 9999, SLOTS - 1):
            assert a.owner(s) == b.owner(s)

    def test_single_member_owns_everything(self):
        ring = HashRing(["only"])
        assert ring.arcs("only") == [(0, SLOTS)]
        assert ring.share("only") == 1.0
        assert ring.arcs("stranger") == []

    def test_member_death_moves_only_its_arc(self):
        before = HashRing(["a", "b", "c"], vnodes=32)
        after = HashRing(["a", "b"], vnodes=32)
        moved = 0
        probes = 2000
        rng = np.random.default_rng(1)
        for s in rng.integers(0, SLOTS, size=probes):
            o1, o2 = before.owner(int(s)), after.owner(int(s))
            if o1 != o2:
                moved += 1
                # only slots c owned may move, and only to survivors
                assert o1 == "c", (s, o1, o2)
        # c owned roughly a third of the ring; nothing else remapped
        assert 0 < moved < 0.6 * probes


# ---------------------------------------------------------------------------
# JobQueueStore (memory backend)
# ---------------------------------------------------------------------------


def _entry(job_id, s=0, payload=None, time_limit=None):
    return {
        "id": job_id,
        "slot": s,
        "bucket": f"tier-{s}",
        "time_limit": time_limit,
        "payload": payload or {},
    }


class TestMemoryQueueStore:
    def test_claim_is_exclusive_and_fifo(self):
        qs = store.get_queue_store()
        qs.enqueue(_entry("j1", 5))
        qs.enqueue(_entry("j2", 5))
        e1 = qs.claim("r1", 5.0)
        e2 = qs.claim("r2", 5.0)
        assert e1["id"] == "j1" and e2["id"] == "j2"
        assert qs.claim("r3", 5.0) is None
        assert qs.depth() == 0

    def test_slot_ranges_filter_claims(self):
        qs = store.get_queue_store()
        qs.enqueue(_entry("low", 10))
        qs.enqueue(_entry("high", 60000))
        assert qs.claim("r1", 5.0, [(0, 100)])["id"] == "low"
        assert qs.claim("r1", 5.0, [(0, 100)]) is None
        assert qs.claim("r1", 5.0, [(50000, SLOTS)])["id"] == "high"

    def test_renew_ack_nack_are_owner_conditional(self):
        qs = store.get_queue_store()
        qs.enqueue(_entry("j1"))
        qs.claim("r1", 5.0)
        assert qs.renew("r1", "j1", 5.0)
        assert not qs.renew("r2", "j1", 5.0)
        assert not qs.ack("r2", "j1")
        assert not qs.nack("r2", "j1")
        assert qs.nack("r1", "j1")  # back to queued, attempt unchanged
        e = qs.claim("r2", 5.0)
        assert e["attempt"] == 0
        assert qs.ack("r2", "j1")
        assert not qs.ack("r2", "j1")  # gone

    def test_expired_lease_reclaims_exactly_once(self):
        qs = store.get_queue_store()
        qs.enqueue(_entry("j1"))
        qs.claim("r1", 0.05)
        time.sleep(0.08)
        req1, dead1 = qs.reclaim_expired()
        req2, dead2 = qs.reclaim_expired()  # a racing peer's scan
        assert [e["id"] for e in req1] == ["j1"] and req1[0]["attempt"] == 1
        assert req2 == [] and dead1 == [] and dead2 == []
        # the crashed owner cannot ack or renew its way back in
        assert not qs.ack("r1", "j1")
        assert not qs.renew("r1", "j1", 5.0)

    def test_second_expiry_is_dead_not_requeued(self):
        qs = store.get_queue_store()
        qs.enqueue(_entry("poison"))
        qs.claim("r1", 0.05)
        time.sleep(0.08)
        req, dead = qs.reclaim_expired()
        assert len(req) == 1 and not dead
        qs.claim("r2", 0.05)
        time.sleep(0.08)
        req, dead = qs.reclaim_expired()
        assert not req and [e["id"] for e in dead] == ["poison"]
        assert dead[0]["attempt"] == 2
        assert qs.claim("r3", 5.0) is None  # removed, not claimable

    def test_renew_keeps_lease_alive_past_ttl(self):
        qs = store.get_queue_store()
        qs.enqueue(_entry("j1"))
        qs.claim("r1", 0.1)
        for _ in range(4):
            time.sleep(0.05)
            assert qs.renew("r1", "j1", 0.1)
        req, dead = qs.reclaim_expired()
        assert not req and not dead

    def test_replica_registry_expires(self):
        qs = store.get_queue_store()
        qs.register_replica("a", 5.0)
        qs.register_replica("b", 0.05)
        time.sleep(0.08)
        assert qs.replicas() == ["a"]

    def test_faulty_plan_injects_into_queue_ops(self, monkeypatch):
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        qs = store.get_queue_store()
        with pytest.raises(Exception):
            qs.enqueue(_entry("j1"))
        with pytest.raises(Exception):
            qs.claim("r1", 5.0)


# ---------------------------------------------------------------------------
# Replica routing (stub runners; no jax)
# ---------------------------------------------------------------------------


def _stub_replica(rid, claims, qs=None, steal=True, **kw):
    """A Replica whose 'scheduler' completes jobs instantly, recording
    (bucket, kind) per claim into `claims[rid]`."""
    qs = qs or store.get_queue_store()
    kinds = {}

    def materialize(entry):
        job = Job(payload={"entry": entry})
        job.id = str(entry["id"])
        return job

    def submit(job):
        entry = job.payload["entry"]
        claims.setdefault(rid, []).append(
            (entry.get("bucket"), kinds.get(job.id, "own"))
        )
        job.result = {"ok": True}
        job.finish("done")

    def on_event(name, **ekw):
        if name == "claim":
            kinds[str(ekw.get("jobId"))] = ekw.get("kind")

    defaults = dict(
        lease_s=2.0, poll_s=0.005, heartbeat_s=0.05, reclaim_s=0.1,
        steal=steal, vnodes=16,
    )
    defaults.update(kw)
    return Replica(
        qs, rid, materialize, submit, on_event=on_event, **defaults
    )


def _wait(cond, timeout=10.0, every=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(every)
    return False


class TestReplicaRouting:
    def test_claims_land_on_ring_owners(self):
        qs = store.get_queue_store()
        claims: dict = {}
        reps = [
            _stub_replica(rid, claims, qs, steal=False)
            for rid in ("rep-a", "rep-b")
        ]
        # register both BEFORE enqueueing so the first ring each
        # replica derives already has two members
        for r in reps:
            qs.register_replica(r.replica_id, 60.0)
        ring = HashRing(["rep-a", "rep-b"], vnodes=16)
        tokens = [f"tier-{i}" for i in range(6)]
        want = {t: ring.owner(slot(t)) for t in tokens}
        n_jobs = 0
        for i in range(18):
            t = tokens[i % len(tokens)]
            qs.enqueue(
                {"id": f"j{i}", "slot": slot(t), "bucket": t, "payload": {}}
            )
            n_jobs += 1
        for r in reps:
            r.start()
        assert _wait(
            lambda: sum(len(v) for v in claims.values()) == n_jobs
        ), claims
        for r in reps:
            r.stop()
        # with stealing OFF every token's jobs went to its ring owner
        for rid, got in claims.items():
            for bucket, kind in got:
                assert want[bucket] == rid, (bucket, rid, want)
                assert kind == "own"

    def test_steal_only_when_own_arc_empty(self):
        qs = store.get_queue_store()
        claims: dict = {}
        # stealer owns nothing that we enqueue: all jobs pinned to the
        # other member's arc
        qs.register_replica("owner", 60.0)
        qs.register_replica("stealer", 60.0)
        ring = HashRing(["owner", "stealer"], vnodes=16)
        owned_by_owner = next(
            s for s in range(0, SLOTS, 911) if ring.owner(s) == "owner"
        )
        for i in range(4):
            qs.enqueue(
                {"id": f"j{i}", "slot": owned_by_owner,
                 "bucket": "hot-tier", "payload": {}}
            )
        rep = _stub_replica("stealer", claims, qs, steal=True)
        rep.start()
        assert _wait(lambda: len(claims.get("stealer", [])) == 4)
        rep.stop()
        assert all(kind == "steal" for _, kind in claims["stealer"])


# ---------------------------------------------------------------------------
# Replica chaos (stub runners; no jax)
# ---------------------------------------------------------------------------


class TestReplicaChaos:
    def test_killed_replica_jobs_reclaimed_exactly_once(self):
        qs = store.get_queue_store()
        done: dict = {}
        done_lock = threading.Lock()

        def materialize(entry):
            job = Job(payload={"entry": entry})
            job.id = str(entry["id"])
            return job

        def blocked_submit(job):
            pass  # claims, then never completes: a wedged box

        def good_submit(job):
            job.result = {"ok": True}
            job.finish("done")

        def complete(job, entry, acked):
            with done_lock:
                done.setdefault(job.id, []).append(
                    (entry.get("attempt"), acked)
                )

        victim = Replica(
            qs, "victim", materialize, blocked_submit, complete=complete,
            lease_s=0.3, poll_s=0.005, heartbeat_s=0.05, reclaim_s=0.05,
        )
        for i in range(4):
            qs.enqueue(_entry(f"j{i}", s=i))
        victim.start()
        assert _wait(lambda: victim.inflight() == 4)
        victim.kill()  # crash WITHOUT acking: leases orphaned

        rescuer = Replica(
            qs, "rescuer", materialize, good_submit, complete=complete,
            lease_s=0.3, poll_s=0.005, heartbeat_s=0.05, reclaim_s=0.05,
        )
        rescuer.start()
        assert _wait(lambda: len(done) == 4), done
        # momentum: let any stray double-completions surface
        time.sleep(0.3)
        rescuer.stop()
        for job_id, completions in done.items():
            assert completions == [(1, True)], (job_id, completions)

    def test_double_crash_fails_clean(self):
        qs = store.get_queue_store()
        dead_seen: list = []

        def materialize(entry):
            job = Job(payload={"entry": entry})
            job.id = str(entry["id"])
            return job

        victims = [
            Replica(
                qs, f"victim{i}", materialize, lambda job: None,
                dead=lambda e: dead_seen.append(e),
                lease_s=0.2, poll_s=0.005, heartbeat_s=0.05,
                reclaim_s=0.05,
            )
            for i in range(2)
        ]
        qs.enqueue(_entry("poison"))
        victims[0].start()
        assert _wait(lambda: victims[0].inflight() == 1)
        victims[0].kill()
        victims[1].start()  # reclaims (attempt 1) and re-claims it
        assert _wait(lambda: victims[1].inflight() == 1, timeout=5)
        victims[1].kill()
        # a healthy third party's scan declares it dead — exactly once
        sentinel = Replica(
            qs, "sentinel", materialize, lambda job: None,
            dead=lambda e: dead_seen.append(e),
            lease_s=0.2, poll_s=0.005, heartbeat_s=0.05, reclaim_s=0.05,
            steal=False,
        )
        sentinel.start()
        assert _wait(lambda: len(dead_seen) == 1, timeout=5), dead_seen
        time.sleep(0.3)
        sentinel.stop()
        assert len(dead_seen) == 1
        assert dead_seen[0]["id"] == "poison"
        assert dead_seen[0]["attempt"] == 2
        assert qs.depth() == 0

    def test_exactly_once_under_faulty_store(self, monkeypatch):
        # every queue-store call fails with probability 0.25 —
        # registration, claims, renews, acks alike: the loop must back
        # off, retry, and still complete every job exactly once (no
        # loss, no duplicates). The memory backend injects BEFORE
        # mutating, so a failed ack never committed and the retry is
        # safe — the same semantics a failed Postgres UPDATE has.
        monkeypatch.setenv("VRPMS_STORE", "faulty:rate=0.25;seed=3")
        qs = store.get_queue_store()
        done: dict = {}
        lock = threading.Lock()

        def materialize(entry):
            job = Job(payload={"entry": entry})
            job.id = str(entry["id"])
            return job

        def submit(job):
            job.result = {"ok": True}
            job.finish("done")

        def complete(job, entry, acked):
            with lock:
                done.setdefault(job.id, []).append(acked)

        rep = Replica(
            qs, "survivor", materialize, submit, complete=complete,
            lease_s=1.0, poll_s=0.005, heartbeat_s=0.05, reclaim_s=0.1,
        )
        rep.start()
        for i in range(5):
            for _ in range(50):
                try:
                    # an injected enqueue failure is the submit path's
                    # 503: the job was never admitted — retry like a
                    # client would
                    qs.enqueue(_entry(f"j{i}", s=i))
                    break
                except Exception:
                    continue
            else:
                raise AssertionError("enqueue never succeeded")
        assert _wait(lambda: len(done) == 5, timeout=20), done
        time.sleep(0.3)
        rep.stop()
        assert all(acks == [True] for acks in done.values()), done


# ---------------------------------------------------------------------------
# Cross-replica chaos with REAL solves (the ISSUE-9 acceptance gate)
# ---------------------------------------------------------------------------


def _seed_dataset(key, n, seed=11):
    rng = np.random.default_rng(seed)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        key, [{"id": i, "demand": 2 if i else 0} for i in range(n)]
    )
    mem.seed_durations(key, d.tolist())


def _solve_content(key, n, seed=1):
    return {
        "problem": "vrp",
        "algorithm": "sa",
        "solutionName": f"dist-{key}",
        "solutionDescription": "t",
        "locationsKey": key,
        "durationsKey": key,
        "capacities": [2 * n] * 3,
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": seed,
        "iterationCount": 200,
        "populationSize": 8,
    }


def _service_replica(rid, runner=None, **kw):
    """A replica wired to the REAL service materialize/complete path,
    executing on its own scheduler — one-replica-per-box in-process."""
    from service import jobs as jobs_mod

    sched = Scheduler(
        runner if runner is not None else jobs_mod._runner,
        queue_limit=64,
        window_s=0.005,
        max_batch=8,
        on_event=jobs_mod._on_event,
        watchdog_s=0,  # the lease layer is the supervision under test
    )
    defaults = dict(
        lease_s=1.0, poll_s=0.01, heartbeat_s=0.1, reclaim_s=0.05,
        vnodes=16,
    )
    defaults.update(kw)
    rep = Replica(
        store.get_queue_store(),
        rid,
        materialize=lambda e: jobs_mod._materialize_entry(e, rid),
        submit=lambda job: sched.submit(
            job, backend=job.payload.get("backend") or "default"
        ),
        complete=jobs_mod._dist_complete,
        dead=jobs_mod._dist_dead,
        **defaults,
    )
    rep._test_scheduler = sched
    return rep


TRACEPARENT = "00-{tid}-{sid}-01"


class TestCrossReplicaChaos:
    def test_mixed_tier_trace_survives_replica_kill_exactly_once(
        self, monkeypatch
    ):
        """Two in-process replicas, one memory-backed queue, a
        mixed-tier trace; the replica holding half the leases dies
        mid-flight. Every job must end `done` EXACTLY once, reclaimed
        jobs under their ORIGINAL trace id at attempt=2."""
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        from vrpms_tpu.sched import ring as ring_mod

        for key, n in (("dq7", 7), ("dq9", 9)):
            _seed_dataset(key, n)
        qs = store.get_queue_store()

        block = threading.Event()

        def blocked_runner(jobs):
            block.wait(timeout=600)  # a wedged box: never completes

        # stealing OFF on both: the claim assignment must stay exactly
        # the ring's, so the victim provably holds its half's leases
        # when it dies and the rescuer only gets them via ring
        # rebalance (membership expiry) + lease reclaim — the crash
        # path, not the work-stealing path
        victim = _service_replica("victim", runner=blocked_runner,
                                  lease_s=0.8, steal=False)
        rescuer = _service_replica("rescuer", lease_s=0.8, steal=False)
        qs.register_replica("victim", 60.0)
        qs.register_replica("rescuer", 60.0)
        ring = HashRing(["victim", "rescuer"], vnodes=16)

        specs = [("dq7", 7), ("dq9", 9)] * 3
        entries, traces = [], {}
        for i, (key, n) in enumerate(specs):
            content = _solve_content(key, n, seed=30 + i)
            # pin half the jobs to each replica's arc via the slot, so
            # the victim definitely claims work before it dies
            target = "victim" if i % 2 == 0 else "rescuer"
            s = next(
                x for x in range(i, SLOTS, 191)
                if ring.owner(x) == target
            )
            tid = uuid.uuid4().hex
            sid = uuid.uuid4().hex[:16]
            job_id = uuid.uuid4().hex[:16]
            traces[job_id] = (tid, target)
            entries.append({
                "id": job_id,
                "slot": s,
                "bucket": f"{key}-tier",
                "time_limit": None,
                "submitted_at": time.time(),
                "payload": {
                    "content": content,
                    "requestId": f"req-{i}",
                    "problem": "vrp",
                    "algorithm": "sa",
                    "traceparent": TRACEPARENT.format(tid=tid, sid=sid),
                },
            })
        for e in entries:
            qs.enqueue(e)
        victim.start()
        rescuer.start()
        # the victim must hold leases before the crash
        assert _wait(lambda: victim.inflight() >= 3, timeout=20)
        victim.kill()

        db = store.get_database("vrp", None)

        def all_done():
            for e in entries:
                rec = db.get_job_seed(e["id"])
                if rec is None or rec.get("status") != "done":
                    return False
            return True

        assert _wait(all_done, timeout=120), {
            e["id"]: db.get_job_seed(e["id"]) for e in entries
        }
        time.sleep(0.5)  # let any stray duplicate publication land
        rescuer.stop()
        victim._test_scheduler.shutdown(timeout=0.2)
        rescuer._test_scheduler.shutdown(timeout=5.0)

        reclaimed = 0
        for e in entries:
            rec = db.get_job_seed(e["id"])
            assert rec["status"] == "done", rec
            tid, target = traces[e["id"]]
            # trace continuity: the record carries the SUBMIT trace id
            assert rec["traceId"] == tid, (rec["traceId"], tid)
            visited = sorted(
                c for v in rec["message"]["vehicles"]
                for c in v["tour"][1:-1]
            )
            n = 7 if "dq7" in e["bucket"] else 9
            assert visited == list(range(1, n)), rec
            if target == "victim":
                # reclaimed from the dead replica: attempt 2, exactly
                # the PR-3 watchdog contract across replicas
                assert rec["attempt"] == 2, rec
                reclaimed += 1
            else:
                assert rec["attempt"] == 1, rec
        assert reclaimed == 3
        assert qs.depth() == 0  # nothing left behind


# ---------------------------------------------------------------------------
# HTTP surface under VRPMS_QUEUE=store
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def server():
    import os

    os.environ["VRPMS_STORE"] = "memory"
    from service import jobs as jobs_mod
    from service.app import serve

    jobs_mod.shutdown_scheduler()
    srv = serve(port=0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    jobs_mod.shutdown_scheduler()


def _post(base, path, body):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=300) as resp:
            return resp.status, json.loads(resp.read()), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), e.headers


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=60) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _poll(base, job_id, timeout=120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status, resp = _get(base, f"/api/jobs/{job_id}")
        assert status == 200, resp
        if resp["job"]["status"] in ("done", "failed"):
            return resp["job"]
        time.sleep(0.05)
    raise AssertionError(f"job {job_id} never finished")


class TestServiceDistHTTP:
    @pytest.fixture(autouse=True)
    def dist_env(self, server, monkeypatch):
        from service import jobs as jobs_mod

        jobs_mod.shutdown_scheduler()
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_LEASE_S", "5")
        monkeypatch.setenv("VRPMS_QUEUE_POLL_MS", "10")
        monkeypatch.setenv("VRPMS_RECLAIM_S", "0.1")
        _seed_dataset("http7", 7)
        yield
        jobs_mod.shutdown_scheduler()

    def test_submit_claim_solve_poll_done(self, server):
        status, resp, _ = _post(
            server, "/api/jobs", _solve_content("http7", 7)
        )
        assert status == 202 and resp["success"], resp
        job = _poll(server, resp["jobId"])
        assert job["status"] == "done", job
        assert job["attempt"] == 1
        visited = sorted(
            c for v in job["message"]["vehicles"] for c in v["tour"][1:-1]
        )
        assert visited == [1, 2, 3, 4, 5, 6]

    def test_ready_reports_replica_and_ring(self, server):
        # force the replica up via one submit
        status, resp, _ = _post(
            server, "/api/jobs", _solve_content("http7", 7, seed=2)
        )
        assert status == 202, resp
        _poll(server, resp["jobId"])
        status, ready = _get(server, "/api/ready")
        assert status == 200, ready
        rep = ready["replica"]
        assert rep["queue"] == "store"
        assert rep["replicaId"]
        assert rep["replicaId"] in rep.get("ringMembers", []), rep
        assert 0.0 < rep["arcShare"] <= 1.0
        assert isinstance(rep["tiersWarmed"], list)

    def test_shared_queue_backpressure_is_429(self, server, monkeypatch):
        # a zero shared bound sheds EVERY submit at the shared-depth
        # check — before the local scheduler is even consulted
        monkeypatch.setenv("VRPMS_SCHED_QUEUE", "0")
        status, resp, headers = _post(
            server, "/api/jobs", _solve_content("http7", 7, seed=3)
        )
        assert status == 429, resp
        assert resp["errors"][0]["what"] == "Too busy"
        assert int(headers["Retry-After"]) >= 1
        # shed at the SHARED-depth check: the job never reached the
        # store queue (and the local scheduler was never consulted)
        assert mem._tables["job_queue"] == {}
        monkeypatch.delenv("VRPMS_SCHED_QUEUE")
        status, resp, _ = _post(
            server, "/api/jobs", _solve_content("http7", 7, seed=4)
        )
        assert status == 202, resp
        assert _poll(server, resp["jobId"])["status"] == "done"

    def test_resolve_of_peer_running_job_is_409(self, server):
        # a job mid-flight on ANOTHER replica (non-terminal record, no
        # live entry here): resolve must refuse — cancellation is
        # replica-local, and proceeding would double-solve
        db = store.get_database("vrp", None)
        db.save_job("peer-job-1", {
            "id": "peer-job-1", "status": "running",
            "problem": "vrp", "algorithm": "sa",
        })
        status, resp, _ = _post(
            server, "/api/jobs/peer-job-1/resolve",
            _solve_content("http7", 7, seed=9),
        )
        assert status == 409, resp
        assert resp["errors"][0]["what"] == "Conflict"
        assert "another replica" in resp["errors"][0]["reason"]

    def test_default_path_does_not_build_a_replica(
        self, server, monkeypatch
    ):
        monkeypatch.delenv("VRPMS_QUEUE", raising=False)
        from service import jobs as jobs_mod

        jobs_mod.shutdown_scheduler()
        status, resp, _ = _post(
            server, "/api/jobs", _solve_content("http7", 7, seed=5)
        )
        assert status == 202, resp
        assert _poll(server, resp["jobId"])["status"] == "done"
        # the local path never touches the distributed machinery
        assert jobs_mod._replica is None
        assert mem._tables["job_queue"] == {}
