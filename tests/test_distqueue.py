"""Distributed job queue tests: ring determinism, lease semantics,
tier-affinity claiming, exactly-once reclaim under crashes and store
faults, and the HTTP surface with VRPMS_QUEUE=store.

Layers:

  * TestRing — consistent-hash units: owner/arcs agreement, full slot
    coverage, bounded movement on membership change;
  * TestMemoryQueueStore — the JobQueueStore contract on the shared
    in-memory backend: exclusive leases, conditional renew/ack/nack,
    exactly-once expiry reclaim with the attempt ceiling;
  * TestReplicaRouting — stub-runner replicas: hash-routed claims land
    on ring owners, off-arc work is stolen only when the own arc is
    empty;
  * TestReplicaChaos — kill a replica mid-flight: peers reclaim its
    leases exactly once, a twice-crashed entry dies clean, and claims
    keep working under a VRPMS_STORE=faulty fault plan;
  * TestCrossReplicaChaos (slow) — the ISSUE-9 acceptance gate with
    REAL solves through the service materialize path: a mixed-tier
    trace across two in-process replicas sharing one memory-backed
    queue, one replica killed mid-flight, every job `done` exactly
    once with trace continuity (same traceId, attempt=2);
  * TestServiceDistHTTP (slow) — the HTTP surface end to end under
    VRPMS_QUEUE=store, readiness ring reporting, shared-depth 429s,
    and the default-path-untouched guard.
"""

import json
import threading
import time
import urllib.error
import urllib.request
import uuid

import numpy as np
import pytest

import store
import store.memory as mem
from store.base import Q_QUEUED
from store.faulty import reset_faults
from vrpms_tpu.sched import Job, Replica, Scheduler
from vrpms_tpu.sched.ring import SLOTS, HashRing, slot


@pytest.fixture(autouse=True)
def clean_store(monkeypatch):
    monkeypatch.setenv("VRPMS_STORE", "memory")
    monkeypatch.delenv("VRPMS_QUEUE", raising=False)
    mem.reset()
    reset_faults()
    yield
    mem.reset()


# ---------------------------------------------------------------------------
# Ring
# ---------------------------------------------------------------------------


class TestRing:
    def test_owner_and_arcs_agree_everywhere(self):
        ring = HashRing(["alpha", "beta", "gamma"], vnodes=16)
        rng = np.random.default_rng(0)
        for s in rng.integers(0, SLOTS, size=500):
            owner = ring.owner(int(s))
            assert any(
                lo <= s < hi for lo, hi in ring.arcs(owner)
            ), (s, owner)
            for m in ring.members:
                if m != owner:
                    assert not any(
                        lo <= s < hi for lo, hi in ring.arcs(m)
                    )

    def test_full_coverage_no_overlap(self):
        ring = HashRing(["a", "b"], vnodes=32)
        covered = sum(
            hi - lo for m in ring.members for lo, hi in ring.arcs(m)
        )
        assert covered == SLOTS

    def test_deterministic_across_instances(self):
        a = HashRing(["r1", "r2", "r3"])
        b = HashRing(["r3", "r1", "r2"])  # order must not matter
        for s in (0, 7, 9999, SLOTS - 1):
            assert a.owner(s) == b.owner(s)

    def test_single_member_owns_everything(self):
        ring = HashRing(["only"])
        assert ring.arcs("only") == [(0, SLOTS)]
        assert ring.share("only") == 1.0
        assert ring.arcs("stranger") == []

    def test_member_death_moves_only_its_arc(self):
        before = HashRing(["a", "b", "c"], vnodes=32)
        after = HashRing(["a", "b"], vnodes=32)
        moved = 0
        probes = 2000
        rng = np.random.default_rng(1)
        for s in rng.integers(0, SLOTS, size=probes):
            o1, o2 = before.owner(int(s)), after.owner(int(s))
            if o1 != o2:
                moved += 1
                # only slots c owned may move, and only to survivors
                assert o1 == "c", (s, o1, o2)
        # c owned roughly a third of the ring; nothing else remapped
        assert 0 < moved < 0.6 * probes


# ---------------------------------------------------------------------------
# JobQueueStore (memory backend)
# ---------------------------------------------------------------------------


def _entry(job_id, s=0, payload=None, time_limit=None):
    return {
        "id": job_id,
        "slot": s,
        "bucket": f"tier-{s}",
        "time_limit": time_limit,
        "payload": payload or {},
    }


class TestMemoryQueueStore:
    def test_claim_is_exclusive_and_fifo(self):
        qs = store.get_queue_store()
        qs.enqueue(_entry("j1", 5))
        qs.enqueue(_entry("j2", 5))
        e1 = qs.claim("r1", 5.0)
        e2 = qs.claim("r2", 5.0)
        assert e1["id"] == "j1" and e2["id"] == "j2"
        assert qs.claim("r3", 5.0) is None
        assert qs.depth() == 0

    def test_slot_ranges_filter_claims(self):
        qs = store.get_queue_store()
        qs.enqueue(_entry("low", 10))
        qs.enqueue(_entry("high", 60000))
        assert qs.claim("r1", 5.0, [(0, 100)])["id"] == "low"
        assert qs.claim("r1", 5.0, [(0, 100)]) is None
        assert qs.claim("r1", 5.0, [(50000, SLOTS)])["id"] == "high"

    def test_renew_ack_nack_are_owner_conditional(self):
        qs = store.get_queue_store()
        qs.enqueue(_entry("j1"))
        qs.claim("r1", 5.0)
        assert qs.renew("r1", "j1", 5.0)
        assert not qs.renew("r2", "j1", 5.0)
        assert not qs.ack("r2", "j1")
        assert not qs.nack("r2", "j1")
        assert qs.nack("r1", "j1")  # back to queued, attempt unchanged
        e = qs.claim("r2", 5.0)
        assert e["attempt"] == 0
        assert qs.ack("r2", "j1")
        assert not qs.ack("r2", "j1")  # gone

    def test_expired_lease_reclaims_exactly_once(self):
        qs = store.get_queue_store()
        qs.enqueue(_entry("j1"))
        qs.claim("r1", 0.05)
        time.sleep(0.08)
        req1, dead1 = qs.reclaim_expired()
        req2, dead2 = qs.reclaim_expired()  # a racing peer's scan
        assert [e["id"] for e in req1] == ["j1"] and req1[0]["attempt"] == 1
        assert req2 == [] and dead1 == [] and dead2 == []
        # the crashed owner cannot ack or renew its way back in
        assert not qs.ack("r1", "j1")
        assert not qs.renew("r1", "j1", 5.0)

    def test_second_expiry_is_dead_not_requeued(self):
        qs = store.get_queue_store()
        qs.enqueue(_entry("poison"))
        qs.claim("r1", 0.05)
        time.sleep(0.08)
        req, dead = qs.reclaim_expired()
        assert len(req) == 1 and not dead
        qs.claim("r2", 0.05)
        time.sleep(0.08)
        req, dead = qs.reclaim_expired()
        assert not req and [e["id"] for e in dead] == ["poison"]
        assert dead[0]["attempt"] == 2
        assert qs.claim("r3", 5.0) is None  # removed, not claimable

    def test_renew_keeps_lease_alive_past_ttl(self):
        qs = store.get_queue_store()
        qs.enqueue(_entry("j1"))
        qs.claim("r1", 0.1)
        for _ in range(4):
            time.sleep(0.05)
            assert qs.renew("r1", "j1", 0.1)
        req, dead = qs.reclaim_expired()
        assert not req and not dead

    def test_replica_registry_expires(self):
        qs = store.get_queue_store()
        qs.register_replica("a", 5.0)
        qs.register_replica("b", 0.05)
        time.sleep(0.08)
        assert qs.replicas() == ["a"]

    def test_faulty_plan_injects_into_queue_ops(self, monkeypatch):
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        qs = store.get_queue_store()
        with pytest.raises(Exception):
            qs.enqueue(_entry("j1"))
        with pytest.raises(Exception):
            qs.claim("r1", 5.0)


# ---------------------------------------------------------------------------
# Replica routing (stub runners; no jax)
# ---------------------------------------------------------------------------


def _stub_replica(rid, claims, qs=None, steal=True, **kw):
    """A Replica whose 'scheduler' completes jobs instantly, recording
    (bucket, kind) per claim into `claims[rid]`."""
    qs = qs or store.get_queue_store()
    kinds = {}

    def materialize(entry):
        job = Job(payload={"entry": entry})
        job.id = str(entry["id"])
        return job

    def submit(job):
        entry = job.payload["entry"]
        claims.setdefault(rid, []).append(
            (entry.get("bucket"), kinds.get(job.id, "own"))
        )
        job.result = {"ok": True}
        job.finish("done")

    def on_event(name, **ekw):
        if name == "claim":
            kinds[str(ekw.get("jobId"))] = ekw.get("kind")

    defaults = dict(
        lease_s=2.0, poll_s=0.005, heartbeat_s=0.05, reclaim_s=0.1,
        steal=steal, vnodes=16,
    )
    defaults.update(kw)
    return Replica(
        qs, rid, materialize, submit, on_event=on_event, **defaults
    )


def _wait(cond, timeout=10.0, every=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(every)
    return False


class TestReplicaRouting:
    def test_claims_land_on_ring_owners(self):
        qs = store.get_queue_store()
        claims: dict = {}
        reps = [
            _stub_replica(rid, claims, qs, steal=False)
            for rid in ("rep-a", "rep-b")
        ]
        # register both BEFORE enqueueing so the first ring each
        # replica derives already has two members
        for r in reps:
            qs.register_replica(r.replica_id, 60.0)
        ring = HashRing(["rep-a", "rep-b"], vnodes=16)
        tokens = [f"tier-{i}" for i in range(6)]
        want = {t: ring.owner(slot(t)) for t in tokens}
        n_jobs = 0
        for i in range(18):
            t = tokens[i % len(tokens)]
            qs.enqueue(
                {"id": f"j{i}", "slot": slot(t), "bucket": t, "payload": {}}
            )
            n_jobs += 1
        for r in reps:
            r.start()
        assert _wait(
            lambda: sum(len(v) for v in claims.values()) == n_jobs
        ), claims
        for r in reps:
            r.stop()
        # with stealing OFF every token's jobs went to its ring owner
        for rid, got in claims.items():
            for bucket, kind in got:
                assert want[bucket] == rid, (bucket, rid, want)
                assert kind == "own"

    def test_steal_only_when_own_arc_empty(self):
        qs = store.get_queue_store()
        claims: dict = {}
        # stealer owns nothing that we enqueue: all jobs pinned to the
        # other member's arc
        qs.register_replica("owner", 60.0)
        qs.register_replica("stealer", 60.0)
        ring = HashRing(["owner", "stealer"], vnodes=16)
        owned_by_owner = next(
            s for s in range(0, SLOTS, 911) if ring.owner(s) == "owner"
        )
        for i in range(4):
            qs.enqueue(
                {"id": f"j{i}", "slot": owned_by_owner,
                 "bucket": "hot-tier", "payload": {}}
            )
        rep = _stub_replica("stealer", claims, qs, steal=True)
        rep.start()
        assert _wait(lambda: len(claims.get("stealer", [])) == 4)
        rep.stop()
        assert all(kind == "steal" for _, kind in claims["stealer"])


# ---------------------------------------------------------------------------
# Replica chaos (stub runners; no jax)
# ---------------------------------------------------------------------------


class TestReplicaChaos:
    def test_killed_replica_jobs_reclaimed_exactly_once(self):
        qs = store.get_queue_store()
        done: dict = {}
        done_lock = threading.Lock()

        def materialize(entry):
            job = Job(payload={"entry": entry})
            job.id = str(entry["id"])
            return job

        def blocked_submit(job):
            pass  # claims, then never completes: a wedged box

        def good_submit(job):
            job.result = {"ok": True}
            job.finish("done")

        def complete(job, entry, acked):
            with done_lock:
                done.setdefault(job.id, []).append(
                    (entry.get("attempt"), acked)
                )

        victim = Replica(
            qs, "victim", materialize, blocked_submit, complete=complete,
            lease_s=0.3, poll_s=0.005, heartbeat_s=0.05, reclaim_s=0.05,
        )
        for i in range(4):
            qs.enqueue(_entry(f"j{i}", s=i))
        victim.start()
        assert _wait(lambda: victim.inflight() == 4)
        victim.kill()  # crash WITHOUT acking: leases orphaned

        rescuer = Replica(
            qs, "rescuer", materialize, good_submit, complete=complete,
            lease_s=0.3, poll_s=0.005, heartbeat_s=0.05, reclaim_s=0.05,
        )
        rescuer.start()
        assert _wait(lambda: len(done) == 4), done
        # momentum: let any stray double-completions surface
        time.sleep(0.3)
        rescuer.stop()
        for job_id, completions in done.items():
            assert completions == [(1, True)], (job_id, completions)

    def test_double_crash_fails_clean(self):
        qs = store.get_queue_store()
        dead_seen: list = []

        def materialize(entry):
            job = Job(payload={"entry": entry})
            job.id = str(entry["id"])
            return job

        victims = [
            Replica(
                qs, f"victim{i}", materialize, lambda job: None,
                dead=lambda e: dead_seen.append(e),
                lease_s=0.2, poll_s=0.005, heartbeat_s=0.05,
                reclaim_s=0.05,
            )
            for i in range(2)
        ]
        qs.enqueue(_entry("poison"))
        victims[0].start()
        assert _wait(lambda: victims[0].inflight() == 1)
        victims[0].kill()
        victims[1].start()  # reclaims (attempt 1) and re-claims it
        assert _wait(lambda: victims[1].inflight() == 1, timeout=5)
        victims[1].kill()
        # a healthy third party's scan declares it dead — exactly once
        sentinel = Replica(
            qs, "sentinel", materialize, lambda job: None,
            dead=lambda e: dead_seen.append(e),
            lease_s=0.2, poll_s=0.005, heartbeat_s=0.05, reclaim_s=0.05,
            steal=False,
        )
        sentinel.start()
        assert _wait(lambda: len(dead_seen) == 1, timeout=5), dead_seen
        time.sleep(0.3)
        sentinel.stop()
        assert len(dead_seen) == 1
        assert dead_seen[0]["id"] == "poison"
        assert dead_seen[0]["attempt"] == 2
        assert qs.depth() == 0

    def test_exactly_once_under_faulty_store(self, monkeypatch):
        # every queue-store call fails with probability 0.25 —
        # registration, claims, renews, acks alike: the loop must back
        # off, retry, and still complete every job exactly once (no
        # loss, no duplicates). The memory backend injects BEFORE
        # mutating, so a failed ack never committed and the retry is
        # safe — the same semantics a failed Postgres UPDATE has.
        monkeypatch.setenv("VRPMS_STORE", "faulty:rate=0.25;seed=3")
        qs = store.get_queue_store()
        done: dict = {}
        lock = threading.Lock()

        def materialize(entry):
            job = Job(payload={"entry": entry})
            job.id = str(entry["id"])
            return job

        def submit(job):
            job.result = {"ok": True}
            job.finish("done")

        def complete(job, entry, acked):
            with lock:
                done.setdefault(job.id, []).append(acked)

        rep = Replica(
            qs, "survivor", materialize, submit, complete=complete,
            lease_s=1.0, poll_s=0.005, heartbeat_s=0.05, reclaim_s=0.1,
        )
        rep.start()
        for i in range(5):
            for _ in range(50):
                try:
                    # an injected enqueue failure is the submit path's
                    # 503: the job was never admitted — retry like a
                    # client would
                    qs.enqueue(_entry(f"j{i}", s=i))
                    break
                except Exception:
                    continue
            else:
                raise AssertionError("enqueue never succeeded")
        assert _wait(lambda: len(done) == 5, timeout=20), done
        time.sleep(0.3)
        rep.stop()
        assert all(acks == [True] for acks in done.values()), done


# ---------------------------------------------------------------------------
# Claim-K-matching: the batched-claims store contract
# ---------------------------------------------------------------------------


class TestClaimBatch:
    def test_leases_same_bucket_oldest_first(self):
        qs = store.get_queue_store()
        for i in range(3):
            qs.enqueue(_entry(f"a{i}", 5))  # bucket tier-5
        qs.enqueue(_entry("b0", 9))  # bucket tier-9
        got = qs.claim_batch("r1", 5.0, 8)
        assert [e["id"] for e in got] == ["a0", "a1", "a2"]
        assert all(e["lease_owner"] == "r1" for e in got)
        assert all(e["state"] == "leased" for e in got)
        assert qs.depth() == 1  # the other token's entry stays queued
        assert qs.claim_batch("r2", 5.0, 8)[0]["id"] == "b0"

    def test_k_caps_the_batch(self):
        qs = store.get_queue_store()
        for i in range(5):
            qs.enqueue(_entry(f"j{i}", 7))
        got = qs.claim_batch("r1", 5.0, 2)
        assert [e["id"] for e in got] == ["j0", "j1"]
        assert qs.depth() == 3

    def test_slots_filter_the_leader(self):
        qs = store.get_queue_store()
        qs.enqueue(_entry("low", 10))
        qs.enqueue(_entry("low2", 10))
        qs.enqueue(_entry("high", 60000))
        got = qs.claim_batch("r1", 5.0, 8, [(0, 100)])
        assert [e["id"] for e in got] == ["low", "low2"]
        assert qs.claim_batch("r1", 5.0, 8, [(0, 100)]) == []

    def test_none_bucket_claims_alone(self):
        qs = store.get_queue_store()
        qs.enqueue({"id": "n1", "slot": 3, "bucket": None, "payload": {}})
        qs.enqueue({"id": "n2", "slot": 3, "bucket": None, "payload": {}})
        got = qs.claim_batch("r1", 5.0, 8)
        assert [e["id"] for e in got] == ["n1"]
        assert qs.depth() == 1

    def test_zero_k_claims_nothing(self):
        qs = store.get_queue_store()
        qs.enqueue(_entry("j1"))
        assert qs.claim_batch("r1", 5.0, 0) == []
        assert qs.depth() == 1

    def test_racing_replicas_split_token_never_share(self):
        qs = store.get_queue_store()
        n = 24
        for i in range(n):
            qs.enqueue(_entry(f"j{i}", 5))
        wins: dict = {}
        lock = threading.Lock()

        def racer(rid):
            while True:
                got = qs.claim_batch(rid, 5.0, 4)
                if not got:
                    return
                with lock:
                    for e in got:
                        wins.setdefault(e["id"], []).append(rid)

        threads = [
            threading.Thread(target=racer, args=(f"r{i}",))
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        # every entry leased EXACTLY once across the racing fleet
        assert sorted(wins) == sorted(f"j{i}" for i in range(n))
        assert all(len(owners) == 1 for owners in wins.values()), wins

    def test_batch_leases_are_per_entry(self):
        # ack one member, let the rest expire: only the unfinished
        # members re-queue, each at attempt+1 — a crash mid-batch
        # reclaims exactly the work that was not done
        qs = store.get_queue_store()
        for i in range(3):
            qs.enqueue(_entry(f"j{i}", 5))
        got = qs.claim_batch("r1", 0.05, 8)
        assert len(got) == 3
        assert qs.ack("r1", "j0")
        time.sleep(0.08)
        req, dead = qs.reclaim_expired()
        assert sorted(e["id"] for e in req) == ["j1", "j2"]
        assert all(e["attempt"] == 1 for e in req)
        assert not dead
        # the acked member is gone for good
        assert all(
            e["id"] != "j0" for e in qs.claim_batch("r2", 5.0, 8)
        )

    def test_base_fallback_serves_single_claims(self):
        # a backend that predates claim_batch still honors the seam at
        # k=1 through the JobQueueStore default
        from store.base import JobQueueStore

        class OneShot(JobQueueStore):
            def __init__(self):
                self.entries = [{"id": "solo"}]

            def claim(self, owner, lease_s, slots=None):
                return self.entries.pop() if self.entries else None

        qs = OneShot()
        assert [e["id"] for e in qs.claim_batch("r", 5.0, 8)] == ["solo"]
        assert qs.claim_batch("r", 5.0, 8) == []

    def test_faulty_plan_injects_into_claim_batch(self, monkeypatch):
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        qs = store.get_queue_store()
        with pytest.raises(Exception):
            qs.claim_batch("r1", 5.0, 4)


# ---------------------------------------------------------------------------
# Assembled-batch gather: the worker side of claim-K
# ---------------------------------------------------------------------------


class TestGatherHint:
    def test_hint_satisfied_skips_the_window(self):
        from vrpms_tpu.sched.batcher import gather_batch
        from vrpms_tpu.sched.queue import JobQueue

        q = JobQueue(8)
        first = Job(payload=None, bucket="b", batch_hint=2)
        mate = Job(payload=None, bucket="b", batch_hint=2)
        q.push(mate)
        t0 = time.monotonic()
        batch = gather_batch(q, first, window_s=5.0, max_batch=8)
        assert len(batch) == 2
        assert time.monotonic() - t0 < 1.0  # never slept out the window

    def test_hint_one_returns_immediately(self):
        from vrpms_tpu.sched.batcher import gather_batch
        from vrpms_tpu.sched.queue import JobQueue

        q = JobQueue(8)
        first = Job(payload=None, bucket="b", batch_hint=1)
        t0 = time.monotonic()
        batch = gather_batch(q, first, window_s=5.0, max_batch=8)
        assert batch == [first]
        assert time.monotonic() - t0 < 1.0

    def test_hint_waits_for_late_mates(self):
        # the hinted mate lands AFTER the leader pops: the gather must
        # pick it up (the window still bounds the wait)
        from vrpms_tpu.sched.batcher import gather_batch
        from vrpms_tpu.sched.queue import JobQueue

        q = JobQueue(8)
        first = Job(payload=None, bucket="b", batch_hint=2)
        mate = Job(payload=None, bucket="b", batch_hint=2)

        def push_late():
            time.sleep(0.05)
            q.push(mate)

        t = threading.Thread(target=push_late)
        t.start()
        batch = gather_batch(q, first, window_s=2.0, max_batch=8)
        t.join()
        assert len(batch) == 2

    def test_leftover_group_never_waits_for_launched_elders(self):
        # a claim of 4 capped by max_batch=3: the first launch takes 3,
        # the leftover (descending hint 1) must launch immediately —
        # not sleep out the window waiting for members already gone
        from vrpms_tpu.sched.batcher import gather_batch
        from vrpms_tpu.sched.queue import JobQueue

        q = JobQueue(8)
        group = [
            Job(payload=None, bucket="b", batch_hint=h)
            for h in (4, 3, 2, 1)
        ]
        for job in group[1:]:
            q.push(job)
        first = gather_batch(q, group[0], window_s=5.0, max_batch=3)
        assert len(first) == 3
        leftover = q.pop(timeout=1.0)
        assert leftover is group[3] and leftover.batch_hint == 1
        t0 = time.monotonic()
        batch = gather_batch(q, leftover, window_s=5.0, max_batch=3)
        assert batch == [leftover]
        assert time.monotonic() - t0 < 1.0

    def test_no_hint_keeps_the_window_contract(self):
        from vrpms_tpu.sched.batcher import gather_batch
        from vrpms_tpu.sched.queue import JobQueue

        q = JobQueue(8)
        first = Job(payload=None, bucket="b")
        t0 = time.monotonic()
        batch = gather_batch(q, first, window_s=0.15, max_batch=8)
        assert batch == [first]
        assert time.monotonic() - t0 >= 0.14  # a local job still waits


# ---------------------------------------------------------------------------
# Replica batched claiming (stub runners; no jax)
# ---------------------------------------------------------------------------


class TestReplicaClaimBatching:
    def _materialize(self, entry):
        job = Job(payload={"entry": entry})
        job.id = str(entry["id"])
        job.bucket = entry.get("bucket")
        return job

    def test_replica_claims_batch_and_sets_hints(self):
        qs = store.get_queue_store()
        sizes: list = []
        hints: dict = {}
        done = threading.Event()
        lock = threading.Lock()

        def submit(job):
            with lock:
                hints[job.id] = job.batch_hint
                if len(hints) == 4:
                    done.set()
            job.result = {"ok": True}
            job.finish("done")

        def on_event(name, **kw):
            if name == "claim_batch":
                sizes.append(kw.get("size"))

        for i in range(4):
            qs.enqueue(_entry(f"j{i}", 5))  # one token, one batch
        rep = Replica(
            qs, "batcher", self._materialize, submit, on_event=on_event,
            lease_s=2.0, poll_s=0.005, heartbeat_s=0.05, reclaim_s=0.5,
        )
        rep.start()
        assert done.wait(timeout=10)
        rep.stop()
        assert sizes and sizes[0] == 4, sizes
        # hints DESCEND through the claim group (4, 3, 2, 1): each
        # member counts itself plus the mates submitted after it, so a
        # leftover gather leader never waits for already-launched elders
        assert sorted(hints) == [f"j{i}" for i in range(4)]
        assert sorted(hints.values(), reverse=True) == [4, 3, 2, 1], hints

    def test_claim_batch_one_restores_single_claims(self):
        qs = store.get_queue_store()
        sizes: list = []
        count = threading.Event()
        seen: list = []

        def submit(job):
            seen.append(job.id)
            if len(seen) == 3:
                count.set()
            job.result = {"ok": True}
            job.finish("done")

        def on_event(name, **kw):
            if name == "claim_batch":
                sizes.append(kw.get("size"))

        for i in range(3):
            qs.enqueue(_entry(f"j{i}", 5))
        rep = Replica(
            qs, "solo", self._materialize, submit, on_event=on_event,
            lease_s=2.0, poll_s=0.005, heartbeat_s=0.05, reclaim_s=0.5,
            claim_batch=1,
        )
        rep.start()
        assert count.wait(timeout=10)
        rep.stop()
        assert sizes and all(s == 1 for s in sizes), sizes

    def test_headroom_clamps_the_claim(self):
        # max_inflight 2 with a submit that never completes: the first
        # claim may lease at most 2 of the 4 queued entries
        qs = store.get_queue_store()
        for i in range(4):
            qs.enqueue(_entry(f"j{i}", 5))
        rep = Replica(
            qs, "narrow", self._materialize, lambda job: None,
            lease_s=5.0, poll_s=0.005, heartbeat_s=0.05, reclaim_s=5.0,
            max_inflight=2,
        )
        rep.start()
        assert _wait(lambda: rep.inflight() == 2, timeout=5)
        time.sleep(0.1)  # more claim rounds run; headroom stays 0
        assert rep.inflight() == 2
        assert qs.depth() == 2  # the rest stay claimable by peers
        rep.kill()

    def test_crash_mid_batch_requeues_only_unfinished(self):
        # the victim claims 4 entries in ONE batch, finishes (and acks)
        # 2, then dies: peers must reclaim exactly the 2 unfinished
        # members at attempt+1 — the finished members never run again
        qs = store.get_queue_store()
        finish_now = {"j0", "j2"}
        completions: dict = {}
        lock = threading.Lock()

        def victim_submit(job):
            if job.id in finish_now:
                job.result = {"ok": True}
                job.finish("done")
            # others: claimed, never completed (a wedged box)

        def complete(job, entry, acked):
            with lock:
                completions.setdefault(job.id, []).append(
                    (entry.get("attempt"), acked)
                )

        for i in range(4):
            qs.enqueue(_entry(f"j{i}", 5))
        victim = Replica(
            qs, "victim", self._materialize, victim_submit,
            complete=complete,
            lease_s=0.3, poll_s=0.005, heartbeat_s=0.05, reclaim_s=0.05,
        )
        victim.start()
        # wait until the finished members were ACKED (completions fire
        # post-ack) and the wedged members hold leases
        assert _wait(
            lambda: len(completions) == 2 and victim.inflight() == 2,
            timeout=10,
        ), completions
        victim.kill()

        def rescue_submit(job):
            job.result = {"ok": True}
            job.finish("done")

        rescuer = Replica(
            qs, "rescuer", self._materialize, rescue_submit,
            complete=complete,
            lease_s=0.3, poll_s=0.005, heartbeat_s=0.05, reclaim_s=0.05,
        )
        rescuer.start()
        assert _wait(lambda: len(completions) == 4, timeout=10), completions
        time.sleep(0.3)  # let any stray duplicate completion land
        rescuer.stop()
        for job_id, comps in completions.items():
            want_attempt = 0 if job_id in finish_now else 1
            assert comps == [(want_attempt, True)], (job_id, comps)
        assert qs.depth() == 0

    def test_exactly_once_under_faulty_store_with_batches(
        self, monkeypatch
    ):
        # the chaos plan now injects into claim_batch too: a same-token
        # backlog under a 25% fault rate must still complete exactly
        # once each, whatever mix of batch sizes the retries produce
        monkeypatch.setenv("VRPMS_STORE", "faulty:rate=0.25;seed=7")
        qs = store.get_queue_store()
        done: dict = {}
        lock = threading.Lock()

        def submit(job):
            job.result = {"ok": True}
            job.finish("done")

        def complete(job, entry, acked):
            with lock:
                done.setdefault(job.id, []).append(acked)

        rep = Replica(
            qs, "survivor", self._materialize, submit, complete=complete,
            lease_s=1.0, poll_s=0.005, heartbeat_s=0.05, reclaim_s=0.1,
        )
        rep.start()
        for i in range(6):
            for _ in range(50):
                try:
                    qs.enqueue(_entry(f"j{i}", 5))
                    break
                except Exception:
                    continue
            else:
                raise AssertionError("enqueue never succeeded")
        assert _wait(lambda: len(done) == 6, timeout=20), done
        time.sleep(0.3)
        rep.stop()
        assert all(acks == [True] for acks in done.values()), done

    def test_claim_mix_tracks_hot_tokens(self):
        # the decayed claim-mix counter: recent tokens dominate, the
        # key set stays bounded — what arc-weighted warmup orders by
        qs = store.get_queue_store()
        rep = Replica(qs, "mixer", lambda e: None, lambda j: None)
        rep._note_claims([{"bucket": "cold"}])
        for _ in range(5):
            rep._note_claims([{"bucket": "hot"}, {"bucket": "hot"}])
        mix = rep.claim_mix()
        assert list(mix)[0] == "hot"
        assert mix["hot"] > mix["cold"]
        # bounded: flooding with distinct tokens evicts the coldest
        for i in range(2 * rep.MIX_KEYS):
            rep._note_claims([{"bucket": f"t{i}"}])
        assert len(rep.claim_mix()) <= rep.MIX_KEYS
        # None tokens never enter the mix
        rep._note_claims([{"bucket": None}])
        assert None not in rep.claim_mix()


# ---------------------------------------------------------------------------
# Shared-depth memo (the 429/readiness store-read cap)
# ---------------------------------------------------------------------------


class TestDepthMemo:
    class _CountingQueue:
        def __init__(self, depth=3):
            self.calls = 0
            self._depth = depth

        def depth(self):
            self.calls += 1
            return self._depth

    def test_memo_caps_store_reads(self, monkeypatch):
        from service import jobs as jobs_mod

        monkeypatch.setenv("VRPMS_DEPTH_MEMO_MS", "60000")
        jobs_mod._depth_memo = None
        qs = self._CountingQueue()
        assert jobs_mod._shared_depth(qs) == 3
        for _ in range(20):
            assert jobs_mod._shared_depth(qs) == 3
        assert qs.calls == 1  # 21 requests, ONE store round trip
        jobs_mod._depth_memo = None

    def test_ttl_zero_reads_through(self, monkeypatch):
        from service import jobs as jobs_mod

        monkeypatch.setenv("VRPMS_DEPTH_MEMO_MS", "0")
        jobs_mod._depth_memo = None
        qs = self._CountingQueue()
        for _ in range(3):
            jobs_mod._shared_depth(qs)
        assert qs.calls == 3
        jobs_mod._depth_memo = None

    def test_unreadable_depth_returns_none(self, monkeypatch):
        from service import jobs as jobs_mod

        monkeypatch.setenv("VRPMS_DEPTH_MEMO_MS", "0")
        jobs_mod._depth_memo = None

        class Down:
            def depth(self):
                raise RuntimeError("store down")

        assert jobs_mod._shared_depth(Down()) is None


# ---------------------------------------------------------------------------
# Arc-weighted warmup ordering
# ---------------------------------------------------------------------------


class TestArcWeightedWarmup:
    class _FakeInst:
        def __init__(self, n):
            self.durations = np.zeros((n, n))
            self.n_vehicles = 3
            self.has_tw = False
            self.het_fleet = False
            self.td_rank = 0

    def test_hot_tiers_order_first(self, monkeypatch):
        from service import jobs as jobs_mod
        from service import warmup as warmup_mod

        monkeypatch.setenv("VRPMS_QUEUE", "store")
        prepared = [
            (8, 3, None, self._FakeInst(8)),
            (16, 3, None, self._FakeInst(16)),
            (24, 3, None, self._FakeInst(24)),
        ]
        hot = jobs_mod.ring_token("vrp", prepared[1][-1])

        class _Rep:
            def claim_mix(self):
                return {hot: 5.0}

        # _hot_first PEEKS the singleton — it must never construct one
        monkeypatch.setattr(jobs_mod, "_replica", _Rep())
        ordered = warmup_mod._hot_first(prepared)
        assert [x[0] for x in ordered] == [16, 8, 24]  # hot first,
        # ladder order preserved for the unclaimed tail

    def test_local_queue_keeps_ladder_order(self, monkeypatch):
        from service import warmup as warmup_mod

        monkeypatch.delenv("VRPMS_QUEUE", raising=False)
        prepared = [
            (8, 3, None, self._FakeInst(8)),
            (16, 3, None, self._FakeInst(16)),
        ]
        assert warmup_mod._hot_first(prepared) == prepared

    def test_empty_mix_keeps_ladder_order(self, monkeypatch):
        from service import jobs as jobs_mod
        from service import warmup as warmup_mod

        monkeypatch.setenv("VRPMS_QUEUE", "store")

        class _Rep:
            def claim_mix(self):
                return {}

        monkeypatch.setattr(jobs_mod, "_replica", _Rep())
        prepared = [
            (8, 3, None, self._FakeInst(8)),
            (16, 3, None, self._FakeInst(16)),
        ]
        assert warmup_mod._hot_first(prepared) == prepared

    def test_no_replica_means_no_construction(self, monkeypatch):
        # VRPMS_QUEUE=store but the claim loop has not started: the
        # ordering helper must return ladder order WITHOUT building
        # (and starting) a replica as a side effect
        from service import jobs as jobs_mod
        from service import warmup as warmup_mod

        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setattr(jobs_mod, "_replica", None)
        constructed: list = []
        # a flag, not a raise: _hot_first swallows exceptions by design,
        # so a raising sentinel could never fail this test
        monkeypatch.setattr(
            jobs_mod, "get_replica", lambda: constructed.append(1)
        )
        prepared = [(8, 3, None, self._FakeInst(8))]
        assert warmup_mod._hot_first(prepared) == prepared
        assert not constructed


# ---------------------------------------------------------------------------
# Cross-replica chaos with REAL solves (the ISSUE-9 acceptance gate)
# ---------------------------------------------------------------------------


def _seed_dataset(key, n, seed=11):
    rng = np.random.default_rng(seed)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        key, [{"id": i, "demand": 2 if i else 0} for i in range(n)]
    )
    mem.seed_durations(key, d.tolist())


def _solve_content(key, n, seed=1):
    return {
        "problem": "vrp",
        "algorithm": "sa",
        "solutionName": f"dist-{key}",
        "solutionDescription": "t",
        "locationsKey": key,
        "durationsKey": key,
        "capacities": [2 * n] * 3,
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": seed,
        "iterationCount": 200,
        "populationSize": 8,
    }


def _service_replica(rid, runner=None, **kw):
    """A replica wired to the REAL service materialize/complete path,
    executing on its own scheduler — one-replica-per-box in-process."""
    from service import jobs as jobs_mod

    sched = Scheduler(
        runner if runner is not None else jobs_mod._runner,
        queue_limit=64,
        window_s=0.005,
        max_batch=8,
        on_event=jobs_mod._on_event,
        watchdog_s=0,  # the lease layer is the supervision under test
    )
    defaults = dict(
        lease_s=1.0, poll_s=0.01, heartbeat_s=0.1, reclaim_s=0.05,
        vnodes=16,
    )
    defaults.update(kw)
    rep = Replica(
        store.get_queue_store(),
        rid,
        materialize=lambda e: jobs_mod._materialize_entry(e, rid),
        submit=lambda job: sched.submit(
            job, backend=job.payload.get("backend") or "default"
        ),
        complete=jobs_mod._dist_complete,
        dead=jobs_mod._dist_dead,
        **defaults,
    )
    rep._test_scheduler = sched
    return rep


TRACEPARENT = "00-{tid}-{sid}-01"


class TestCrossReplicaChaos:
    def test_mixed_tier_trace_survives_replica_kill_exactly_once(
        self, monkeypatch
    ):
        """Two in-process replicas, one memory-backed queue, a
        mixed-tier trace; the replica holding half the leases dies
        mid-flight. Every job must end `done` EXACTLY once, reclaimed
        jobs under their ORIGINAL trace id at attempt=2."""
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        from vrpms_tpu.sched import ring as ring_mod

        for key, n in (("dq7", 7), ("dq9", 9)):
            _seed_dataset(key, n)
        qs = store.get_queue_store()

        block = threading.Event()

        def blocked_runner(jobs):
            block.wait(timeout=600)  # a wedged box: never completes

        # stealing OFF on both: the claim assignment must stay exactly
        # the ring's, so the victim provably holds its half's leases
        # when it dies and the rescuer only gets them via ring
        # rebalance (membership expiry) + lease reclaim — the crash
        # path, not the work-stealing path
        victim = _service_replica("victim", runner=blocked_runner,
                                  lease_s=0.8, steal=False)
        rescuer = _service_replica("rescuer", lease_s=0.8, steal=False)
        qs.register_replica("victim", 60.0)
        qs.register_replica("rescuer", 60.0)
        ring = HashRing(["victim", "rescuer"], vnodes=16)

        specs = [("dq7", 7), ("dq9", 9)] * 3
        entries, traces = [], {}
        for i, (key, n) in enumerate(specs):
            content = _solve_content(key, n, seed=30 + i)
            # pin half the jobs to each replica's arc via the slot, so
            # the victim definitely claims work before it dies
            target = "victim" if i % 2 == 0 else "rescuer"
            s = next(
                x for x in range(i, SLOTS, 191)
                if ring.owner(x) == target
            )
            tid = uuid.uuid4().hex
            sid = uuid.uuid4().hex[:16]
            job_id = uuid.uuid4().hex[:16]
            traces[job_id] = (tid, target)
            entries.append({
                "id": job_id,
                "slot": s,
                "bucket": f"{key}-tier",
                "time_limit": None,
                "submitted_at": time.time(),
                "payload": {
                    "content": content,
                    "requestId": f"req-{i}",
                    "problem": "vrp",
                    "algorithm": "sa",
                    "traceparent": TRACEPARENT.format(tid=tid, sid=sid),
                },
            })
        for e in entries:
            qs.enqueue(e)
        victim.start()
        rescuer.start()
        # the victim must hold leases before the crash
        assert _wait(lambda: victim.inflight() >= 3, timeout=20)
        victim.kill()

        db = store.get_database("vrp", None)

        def all_done():
            for e in entries:
                rec = db.get_job_seed(e["id"])
                if rec is None or rec.get("status") != "done":
                    return False
            return True

        assert _wait(all_done, timeout=120), {
            e["id"]: db.get_job_seed(e["id"]) for e in entries
        }
        time.sleep(0.5)  # let any stray duplicate publication land
        rescuer.stop()
        victim._test_scheduler.shutdown(timeout=0.2)
        rescuer._test_scheduler.shutdown(timeout=5.0)

        reclaimed = 0
        for e in entries:
            rec = db.get_job_seed(e["id"])
            assert rec["status"] == "done", rec
            tid, target = traces[e["id"]]
            # trace continuity: the record carries the SUBMIT trace id
            assert rec["traceId"] == tid, (rec["traceId"], tid)
            visited = sorted(
                c for v in rec["message"]["vehicles"]
                for c in v["tour"][1:-1]
            )
            n = 7 if "dq7" in e["bucket"] else 9
            assert visited == list(range(1, n)), rec
            if target == "victim":
                # reclaimed from the dead replica: attempt 2, exactly
                # the PR-3 watchdog contract across replicas
                assert rec["attempt"] == 2, rec
                reclaimed += 1
            else:
                assert rec["attempt"] == 1, rec
        assert reclaimed == 3
        assert qs.depth() == 0  # nothing left behind


class TestClaimKCrossReplica:
    def test_kill_mid_batch_requeues_only_unfinished_members(
        self, monkeypatch
    ):
        """The claim-K acceptance gate with REAL solves: one claim
        leases 4 same-token entries (2 launch buckets); the victim
        solves and acks the first bucket's pair, wedges on the second,
        and dies. Only the unfinished pair may requeue — at attempt=2,
        under their ORIGINAL trace ids — while the finished pair keeps
        its attempt=1 records untouched."""
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        _seed_dataset("dqk9", 9)
        qs = store.get_queue_store()

        block = threading.Event()
        BLOCK_ITERS = 250  # bucket B: wedges the victim
        DONE_ITERS = 200   # bucket A: solves normally

        from service import jobs as jobs_mod

        def selective_runner(jobs):
            iters = {
                int(j.payload["prep"].opts.get("iteration_count") or 0)
                for j in jobs
            }
            if BLOCK_ITERS in iters:
                block.wait(timeout=600)  # a wedged box
                return
            jobs_mod._runner(jobs)

        sizes: list = []

        def victim_events(name, **kw):
            if name == "claim_batch":
                sizes.append(kw.get("size"))

        try:
            victim = _service_replica(
                "victim", runner=selective_runner, lease_s=0.8,
                steal=False, on_event=victim_events,
            )
            rescuer = _service_replica("rescuer", lease_s=0.8, steal=False)
            qs.register_replica("victim", 60.0)
            qs.register_replica("rescuer", 60.0)
            ring = HashRing(["victim", "rescuer"], vnodes=16)
            # every entry shares ONE ring token, pinned to the victim's
            # arc — claimed together in one batch
            s = next(
                x for x in range(0, SLOTS, 191)
                if ring.owner(x) == "victim"
            )
            entries, traces = [], {}
            specs = [DONE_ITERS, DONE_ITERS, BLOCK_ITERS, BLOCK_ITERS]
            for i, iters in enumerate(specs):
                content = dict(
                    _solve_content("dqk9", 9, seed=70 + i),
                    iterationCount=iters,
                )
                tid = uuid.uuid4().hex
                sid = uuid.uuid4().hex[:16]
                job_id = uuid.uuid4().hex[:16]
                traces[job_id] = (tid, iters)
                entries.append({
                    "id": job_id,
                    "slot": s,
                    "bucket": "dqk9-token",
                    "time_limit": None,
                    "submitted_at": time.time(),
                    "payload": {
                        "content": content,
                        "requestId": f"req-k{i}",
                        "problem": "vrp",
                        "algorithm": "sa",
                        "traceparent": TRACEPARENT.format(tid=tid, sid=sid),
                    },
                })
            for e in entries:
                qs.enqueue(e)
            victim.start()
            rescuer.start()

            db = store.get_database("vrp", None)
            done_ids = [
                jid for jid, (_, iters) in traces.items()
                if iters == DONE_ITERS
            ]
            wedged_ids = [
                jid for jid, (_, iters) in traces.items()
                if iters == BLOCK_ITERS
            ]

            def group_done(ids):
                def check():
                    for jid in ids:
                        rec = db.get_job_seed(jid)
                        if rec is None or rec.get("status") != "done":
                            return False
                    return True
                return check

            # bucket A solved + acked on the victim BEFORE the crash
            assert _wait(group_done(done_ids), timeout=120), {
                jid: db.get_job_seed(jid) for jid in done_ids
            }
            assert sizes and sizes[0] == 4, sizes  # ONE claim, all 4
            victim.kill()
            # bucket B reclaimed and completed by the rescuer
            assert _wait(group_done(wedged_ids), timeout=120), {
                jid: db.get_job_seed(jid) for jid in wedged_ids
            }
            time.sleep(0.5)  # let any stray duplicate publication land
        finally:
            block.set()  # release the wedged worker
            victim.kill()
            rescuer.stop()
            victim._test_scheduler.shutdown(timeout=0.2)
            rescuer._test_scheduler.shutdown(timeout=5.0)

        for jid, (tid, iters) in traces.items():
            rec = db.get_job_seed(jid)
            assert rec["status"] == "done", rec
            assert rec["traceId"] == tid, (rec["traceId"], tid)
            visited = sorted(
                c for v in rec["message"]["vehicles"]
                for c in v["tour"][1:-1]
            )
            assert visited == list(range(1, 9)), rec
            if iters == DONE_ITERS:
                # finished mid-batch members: never reclaimed
                assert rec["attempt"] == 1, rec
            else:
                # unfinished members: exactly one reclaim generation
                assert rec["attempt"] == 2, rec
        assert qs.depth() == 0


# ---------------------------------------------------------------------------
# HTTP surface under VRPMS_QUEUE=store
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def server():
    import os

    os.environ["VRPMS_STORE"] = "memory"
    from service import jobs as jobs_mod
    from service.app import serve

    jobs_mod.shutdown_scheduler()
    srv = serve(port=0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    jobs_mod.shutdown_scheduler()


def _post(base, path, body):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=300) as resp:
            return resp.status, json.loads(resp.read()), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), e.headers


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=60) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _poll(base, job_id, timeout=120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status, resp = _get(base, f"/api/jobs/{job_id}")
        assert status == 200, resp
        if resp["job"]["status"] in ("done", "failed"):
            return resp["job"]
        time.sleep(0.05)
    raise AssertionError(f"job {job_id} never finished")


class TestServiceDistHTTP:
    @pytest.fixture(autouse=True)
    def dist_env(self, server, monkeypatch):
        from service import jobs as jobs_mod

        jobs_mod.shutdown_scheduler()
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_LEASE_S", "5")
        monkeypatch.setenv("VRPMS_QUEUE_POLL_MS", "10")
        monkeypatch.setenv("VRPMS_RECLAIM_S", "0.1")
        _seed_dataset("http7", 7)
        yield
        jobs_mod.shutdown_scheduler()

    def test_submit_claim_solve_poll_done(self, server):
        status, resp, _ = _post(
            server, "/api/jobs", _solve_content("http7", 7)
        )
        assert status == 202 and resp["success"], resp
        job = _poll(server, resp["jobId"])
        assert job["status"] == "done", job
        assert job["attempt"] == 1
        visited = sorted(
            c for v in job["message"]["vehicles"] for c in v["tour"][1:-1]
        )
        assert visited == [1, 2, 3, 4, 5, 6]

    def test_ready_reports_replica_and_ring(self, server):
        # force the replica up via one submit
        status, resp, _ = _post(
            server, "/api/jobs", _solve_content("http7", 7, seed=2)
        )
        assert status == 202, resp
        _poll(server, resp["jobId"])
        status, ready = _get(server, "/api/ready")
        assert status == 200, ready
        rep = ready["replica"]
        assert rep["queue"] == "store"
        assert rep["replicaId"]
        assert rep["replicaId"] in rep.get("ringMembers", []), rep
        assert 0.0 < rep["arcShare"] <= 1.0
        assert isinstance(rep["tiersWarmed"], list)

    def test_shared_queue_backpressure_is_429(self, server, monkeypatch):
        # a zero shared bound sheds EVERY submit at the shared-depth
        # check — before the local scheduler is even consulted
        monkeypatch.setenv("VRPMS_SCHED_QUEUE", "0")
        status, resp, headers = _post(
            server, "/api/jobs", _solve_content("http7", 7, seed=3)
        )
        assert status == 429, resp
        assert resp["errors"][0]["what"] == "Too busy"
        assert int(headers["Retry-After"]) >= 1
        # shed at the SHARED-depth check: the job never reached the
        # store queue (and the local scheduler was never consulted)
        assert mem._tables["job_queue"] == {}
        monkeypatch.delenv("VRPMS_SCHED_QUEUE")
        status, resp, _ = _post(
            server, "/api/jobs", _solve_content("http7", 7, seed=4)
        )
        assert status == 202, resp
        assert _poll(server, resp["jobId"])["status"] == "done"

    def test_resolve_of_peer_running_job_is_409(self, server):
        # a job mid-flight on ANOTHER replica (non-terminal record, no
        # live entry here): resolve must refuse — cancellation is
        # replica-local, and proceeding would double-solve
        db = store.get_database("vrp", None)
        db.save_job("peer-job-1", {
            "id": "peer-job-1", "status": "running",
            "problem": "vrp", "algorithm": "sa",
        })
        status, resp, _ = _post(
            server, "/api/jobs/peer-job-1/resolve",
            _solve_content("http7", 7, seed=9),
        )
        assert status == 409, resp
        assert resp["errors"][0]["what"] == "Conflict"
        assert "another replica" in resp["errors"][0]["reason"]

    def test_default_path_does_not_build_a_replica(
        self, server, monkeypatch
    ):
        monkeypatch.delenv("VRPMS_QUEUE", raising=False)
        from service import jobs as jobs_mod

        jobs_mod.shutdown_scheduler()
        status, resp, _ = _post(
            server, "/api/jobs", _solve_content("http7", 7, seed=5)
        )
        assert status == 202, resp
        assert _poll(server, resp["jobId"])["status"] == "done"
        # the local path never touches the distributed machinery
        assert jobs_mod._replica is None
        assert mem._tables["job_queue"] == {}
