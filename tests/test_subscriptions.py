"""Standing subscriptions (ISSUE 17): durable re-solve-on-change jobs
with delta feeds, debounced coalescing, and lineage streaming.

Layers, bottom up: delta composition (coalescing algebra: cancel-outs,
duplicate rejection, attribute merge), the store subscription seam
(put/get/list/delete, bounded memory table, fail-open under fault
plans), the create/delta/delete HTTP contracts, per-tenant quota
counting, fleet adoption rules (live owners keep their docs, dead
owners' docs are taken over, local mode adopts everything), drain
parking.

End-to-end layers (slow; tier1.yml runs the file in full): a K-delta
burst coalesces to exactly ONE generation, no-op bursts (adds cancelled
by drops) dedupe on the tier fingerprint with ZERO launches, the
generation chain records `resolvedFrom` lineage in records + timeline +
`sub.generation` trace roots, cadence re-solves fire without deltas,
the SSE stream replays generations Last-Event-ID aware, a killed
manager's pending delta resumes on an adopting manager as a
trigger="resume" generation seeded from the last incumbent, and
VRPMS_SUBS=off 404s the routes while keeping fixed-seed job responses
byte-identical.
"""

from __future__ import annotations

import io
import json
import threading
import time

import numpy as np
import pytest

import store
import store.memory as mem
from service import jobs as jobs_mod
from service import obs as service_obs
from service import subscriptions as subs_mod
from service.app import serve
from store.faulty import reset_faults
from store.resilient import reset_resilience
from vrpms_tpu.obs import spans


@pytest.fixture(autouse=True)
def clean(monkeypatch):
    monkeypatch.setenv("VRPMS_STORE", "memory")
    mem.reset()
    reset_faults()
    reset_resilience()
    subs_mod.reset()
    yield
    subs_mod.reset()
    jobs_mod.shutdown_scheduler()
    mem.reset()
    reset_faults()
    reset_resilience()


def _wait(cond, timeout=60.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(every)
    return False


def _seed_dataset(key, n, seed=11):
    rng = np.random.default_rng(seed)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        key, [{"id": i, "demand": 2 if i else 0} for i in range(n)]
    )
    mem.seed_durations(key, d.tolist())


def _sub_content(key, n, seed=1, **over):
    content = {
        "problem": "vrp",
        "algorithm": "sa",
        "solutionName": f"sub-{key}",
        "solutionDescription": "t",
        "locationsKey": key,
        "durationsKey": key,
        "capacities": [2 * n] * 3,
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": seed,
        "iterationCount": 600,
        "populationSize": 8,
    }
    content.update(over)
    return content


def _metric(name, **labels) -> float:
    """Read a counter back out of the rendered exposition (the public
    surface, so these tests also guard the metric/label names)."""
    text = service_obs.REGISTRY.render()
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        if labels and not all(
            f'{k}="{v}"' in line for k, v in labels.items()
        ):
            continue
        if line.startswith(name + "{") or line.startswith(name + " "):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def _wait_generation(sub_id, gen, timeout=90.0):
    return _wait(
        lambda: int(
            (subs_mod.manager().lookup(sub_id) or {}).get("generation")
            or 0
        ) >= gen,
        timeout=timeout,
    )


def _wait_job_done(job_id, timeout=90.0):
    db = store.get_database("vrp", None)

    def done():
        rec = db.get_job(job_id, [])
        return rec is not None and rec.get("status") in ("done", "failed")

    return _wait(done, timeout=timeout)


# ---------------------------------------------------------------------------
# Delta composition (the coalescing algebra)
# ---------------------------------------------------------------------------


class TestComposeDelta:
    def test_accumulates_and_merges_attributes(self):
        errors: list = []
        cum = subs_mod._compose_delta({}, {"add": [5]}, errors)
        assert cum == {"add": [5]} and not errors
        cum = subs_mod._compose_delta(
            cum, {"drop": [3], "demands": {"5": 4}}, errors
        )
        assert cum == {"add": [5], "drop": [3], "demands": {"5": 4}}
        cum = subs_mod._compose_delta(cum, {"demands": {"5": 9}}, errors)
        assert cum["demands"] == {"5": 9} and not errors

    def test_add_then_drop_cancels_out(self):
        errors: list = []
        cum = subs_mod._compose_delta({}, {"add": [5, 6]}, errors)
        cum = subs_mod._compose_delta(cum, {"drop": [5]}, errors)
        assert cum == {"add": [6]} and not errors
        cum = subs_mod._compose_delta(cum, {"drop": [6]}, errors)
        assert cum == {}  # a fully-cancelled burst is a net no-op

    def test_drop_then_add_cancels_out(self):
        errors: list = []
        cum = subs_mod._compose_delta({}, {"drop": [4]}, errors)
        cum = subs_mod._compose_delta(cum, {"add": [4]}, errors)
        assert cum == {} and not errors

    def test_duplicate_add_rejected(self):
        errors: list = []
        cum = subs_mod._compose_delta({}, {"add": [5]}, errors)
        assert subs_mod._compose_delta(cum, {"add": [5]}, errors) is None
        assert any("duplicate add" in e["reason"] for e in errors)

    def test_duplicate_drop_rejected(self):
        errors: list = []
        cum = subs_mod._compose_delta({}, {"drop": [5]}, errors)
        assert subs_mod._compose_delta(cum, {"drop": [5]}, errors) is None
        assert any("duplicate drop" in e["reason"] for e in errors)

    def test_add_and_drop_same_id_rejected(self):
        errors: list = []
        out = subs_mod._compose_delta(
            {}, {"add": [5], "drop": [5]}, errors
        )
        assert out is None and errors

    def test_unknown_key_and_shape_rejected(self):
        errors: list = []
        assert subs_mod._compose_delta({}, {"bogus": 1}, errors) is None
        assert subs_mod._compose_delta({}, "not-a-dict", []) is None
        assert subs_mod._compose_delta({}, {"add": "x"}, []) is None


# ---------------------------------------------------------------------------
# Store seam
# ---------------------------------------------------------------------------


class TestSubscriptionStoreSeam:
    def test_put_get_list_delete(self):
        db = store.get_database("vrp", None)
        assert db.get_subscription("s1") is None
        assert db.put_subscription("s1", {"id": "s1", "generation": 0})
        assert db.put_subscription("s2", {"id": "s2", "generation": 3})
        assert db.get_subscription("s1")["generation"] == 0
        docs = db.list_subscriptions()
        assert {d["id"] for d in docs} == {"s1", "s2"}
        assert db.delete_subscription("s1")
        assert db.get_subscription("s1") is None
        assert len(db.list_subscriptions()) == 1

    def test_memory_table_is_bounded(self):
        db = store.get_database("vrp", None)
        cap = mem._InMemoryMixin.MAX_SUBSCRIPTIONS
        for i in range(cap + 10):
            db.put_subscription(f"s{i}", {"id": f"s{i}"})
        with mem._lock:
            assert len(mem._tables["subscriptions"]) == cap

    def test_fail_open_under_down_plan(self, monkeypatch):
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        reset_resilience()
        db = store.get_database("vrp", None)
        assert db.put_subscription("s1", {"id": "s1"}) is False
        assert db.get_subscription("s1") is None
        # list distinguishes unknown (None) from empty ([]) so cadence
        # adopters never conclude "no standing work" from a read blip
        assert db.list_subscriptions() is None
        assert db.delete_subscription("s1") is False


# ---------------------------------------------------------------------------
# Create / delta / delete contracts (no solver runs)
# ---------------------------------------------------------------------------


class TestContracts:
    def test_create_registers_without_launching(self):
        _seed_dataset("subc", 8)
        code, body = subs_mod.manager().create(_sub_content("subc", 8))
        assert code == 201 and body["success"], body
        sid = body["subscriptionId"]
        doc = subs_mod.manager().lookup(sid)
        assert doc["generation"] == 0 and doc["lastJobId"] is None
        assert doc["status"] == "active"
        # durable from birth: the store row exists before any delta
        assert store.get_database("vrp", None).get_subscription(sid)
        code, body = subs_mod.manager().list()
        assert code == 200
        assert sid in {
            v["subscriptionId"] for v in body["subscriptions"]
        }

    def test_create_rejects_bad_resolve_every_and_inline_delta(self):
        _seed_dataset("subc2", 8)
        mgr = subs_mod.manager()
        code, body = mgr.create(
            _sub_content("subc2", 8, resolveEvery="soon")
        )
        assert code == 400 and not body["success"]
        code, body = mgr.create(_sub_content("subc2", 8, resolveEvery=-1))
        assert code == 400
        code, body = mgr.create(
            _sub_content("subc2", 8, delta={"add": [3]})
        )
        assert code == 400
        assert any("deltas" in e["reason"] for e in body["errors"])

    def test_create_rejects_unparseable_dataset(self):
        code, body = subs_mod.manager().create(
            _sub_content("no-such-key", 8)
        )
        assert code == 400 and body["errors"]

    def test_unknown_subscription_404s(self):
        mgr = subs_mod.manager()
        code, _ = mgr.post_delta("nope", {"add": [1]})
        assert code == 404
        assert mgr.lookup("nope") is None
        code, _ = mgr.delete("nope")
        assert code == 404

    def test_malformed_delta_rejects_without_arming(self):
        _seed_dataset("subc3", 8)
        mgr = subs_mod.manager()
        _, body = mgr.create(_sub_content("subc3", 8))
        sid = body["subscriptionId"]
        code, body = mgr.post_delta(sid, {"bogus": [1]})
        assert code == 400
        doc = mgr.lookup(sid)
        assert doc["pendingCount"] == 0

    def test_delta_accepts_and_counts_pending(self, monkeypatch):
        monkeypatch.setenv("VRPMS_SUB_DEBOUNCE_MS", "60000")
        _seed_dataset("subc4", 8)
        mgr = subs_mod.manager()
        _, body = mgr.create(_sub_content("subc4", 8))
        sid = body["subscriptionId"]
        before = _metric("vrpms_sub_coalesced_total")
        code, body = mgr.post_delta(sid, {"add": [3]})
        assert code == 202 and body["pendingDeltas"] == 1
        code, body = mgr.post_delta(sid, {"drop": [4]})
        assert code == 202 and body["pendingDeltas"] == 2
        # the second delta of the window is one coalesced launch saved
        assert _metric("vrpms_sub_coalesced_total") == before + 1
        # pending state is durable (the drain/crash adoption seed)
        row = store.get_database("vrp", None).get_subscription(sid)
        assert row["pending"] == {"add": [3], "drop": [4]}

    def test_concurrent_due_sweeps_fire_once(self, monkeypatch):
        # run_due is entered from BOTH the worker thread and the
        # replica heartbeat: the due-collection must claim the
        # deadline under the lock, or one burst launches twice
        monkeypatch.setenv("VRPMS_SUB_DEBOUNCE_MS", "60000")
        _seed_dataset("subc6", 8)
        mgr = subs_mod.manager()
        _, body = mgr.create(_sub_content("subc6", 8))
        sid = body["subscriptionId"]
        mgr.post_delta(sid, {"add": [3]})
        fired: list = []
        monkeypatch.setattr(
            mgr, "_fire", lambda s, trigger: fired.append((s, trigger))
        )
        with mgr._lock:
            mgr._subs[sid].fire_at = time.monotonic() - 1.0  # due now
        mgr.run_due()  # the worker sweep claims the deadline...
        mgr.run_due()  # ...so the heartbeat sweep finds nothing due
        assert fired == [(sid, "delta")]

    def test_failed_store_delete_leaves_tombstone_not_zombie(
        self, monkeypatch
    ):
        monkeypatch.setenv("VRPMS_SUB_DEBOUNCE_MS", "60000")
        _seed_dataset("subc7", 8)
        mgr = subs_mod.manager()
        _, body = mgr.create(_sub_content("subc7", 8))
        sid = body["subscriptionId"]
        db = store.get_database("vrp", None)
        real_delete = type(db).delete_subscription
        failing = {"on": True}
        monkeypatch.setattr(
            type(db),
            "delete_subscription",
            lambda self, s: (
                False if failing["on"] else real_delete(self, s)
            ),
        )
        code, body = mgr.delete(sid)
        assert code == 200 and body["status"] == "deleted"
        assert "degraded" not in body  # the tombstone write stuck
        # the tombstone hides the row from every read surface
        assert mgr.lookup(sid) is None
        _, lst = mgr.list()
        assert sid not in {
            v["subscriptionId"] for v in lst["subscriptions"]
        }
        assert mgr.post_delta(sid, {"add": [3]})[0] == 404
        # the adoption sweep must NOT resurrect the deleted sub
        mgr.tick()
        assert mgr.stats()["count"] == 0
        # once the store delete works again the sweep drops the row
        failing["on"] = False
        mgr.tick()
        assert db.get_subscription(sid) is None

    def test_delete_is_terminal_and_clears_store(self, monkeypatch):
        monkeypatch.setenv("VRPMS_SUB_DEBOUNCE_MS", "60000")
        _seed_dataset("subc5", 8)
        mgr = subs_mod.manager()
        _, body = mgr.create(_sub_content("subc5", 8))
        sid = body["subscriptionId"]
        mgr.post_delta(sid, {"add": [3]})  # pending must not leak
        code, body = mgr.delete(sid)
        assert code == 200 and body["status"] == "deleted"
        assert body["cancelRequested"] is False  # nothing in flight
        assert mgr.lookup(sid) is None
        assert store.get_database("vrp", None).get_subscription(sid) is None
        # deleting the registry entry killed the armed debounce timer:
        # a later due-sweep has nothing to fire
        mgr.run_due()
        assert mgr.stats()["count"] == 0


# ---------------------------------------------------------------------------
# Tenant quota counting
# ---------------------------------------------------------------------------


class TestTenantQuota:
    def test_identified_tenant_capped_and_freed_by_delete(
        self, monkeypatch
    ):
        monkeypatch.setenv("VRPMS_SUB_MAX_PER_TENANT", "1")
        _seed_dataset("subq", 8)
        mgr = subs_mod.manager()
        code, body = mgr.create(_sub_content("subq", 8, auth="tok-a"))
        assert code == 201
        first = body["subscriptionId"]
        code, body = mgr.create(_sub_content("subq", 8, auth="tok-a"))
        assert code == 429
        assert body["errors"][0]["what"] == "Too busy"
        # another tenant is unaffected by tok-a's quota
        code, _ = mgr.create(_sub_content("subq", 8, auth="tok-b"))
        assert code == 201
        # deleting frees the slot
        mgr.delete(first)
        code, _ = mgr.create(_sub_content("subq", 8, auth="tok-a"))
        assert code == 201

    def test_anonymous_exempt(self, monkeypatch):
        # quotas apply only to identified tenants (the QoS rule)
        monkeypatch.setenv("VRPMS_SUB_MAX_PER_TENANT", "1")
        _seed_dataset("subq2", 8)
        mgr = subs_mod.manager()
        for _ in range(3):
            code, _ = mgr.create(_sub_content("subq2", 8))
            assert code == 201


# ---------------------------------------------------------------------------
# Fleet adoption rules + drain parking + stats
# ---------------------------------------------------------------------------


class _FakeRing:
    def __init__(self, members):
        self.members = members


class _FakeReplica:
    # shutdown_scheduler may see the fake during teardown: present the
    # already-draining surface so it only calls stop()
    draining = True

    def __init__(self, members):
        self._members = members

    def ring(self):
        return _FakeRing(self._members)

    def stop(self, drain_s=None):
        pass


class TestAdoption:
    def test_local_mode_adopts_everything(self):
        _seed_dataset("suba", 8)
        mgr = subs_mod.manager()
        _, body = mgr.create(_sub_content("suba", 8))
        sid = body["subscriptionId"]
        subs_mod.reset()  # the process "restarts": registry gone
        mgr = subs_mod.manager()
        assert mgr.stats()["count"] == 0
        mgr.tick()
        assert mgr.stats()["count"] == 1
        doc = mgr.lookup(sid)
        assert doc["replicaId"] == jobs_mod.replica_id()

    def test_fleet_mode_respects_live_owners(self, monkeypatch):
        _seed_dataset("subf", 8)
        mgr = subs_mod.manager()
        for owner in ("alive-peer", "dead-peer"):
            _, body = mgr.create(_sub_content("subf", 8))
            doc = store.get_database("vrp", None).get_subscription(
                body["subscriptionId"]
            )
            doc["replicaId"] = owner
            doc["_probe"] = owner
            store.get_database("vrp", None).put_subscription(
                doc["id"], doc
            )
        subs_mod.reset()
        monkeypatch.setattr(jobs_mod, "dist_queue_enabled", lambda: True)
        monkeypatch.setattr(
            jobs_mod, "_replica", _FakeReplica(["alive-peer", "me"])
        )
        monkeypatch.setattr(jobs_mod, "replica_id", lambda: "me")
        mgr = subs_mod.manager()
        mgr.tick()
        # only the dead peer's doc was taken over
        assert mgr.stats()["count"] == 1
        rows = store.get_database("vrp", None).list_subscriptions()
        owners = {d["_probe"]: d["replicaId"] for d in rows}
        assert owners["alive-peer"] == "alive-peer"
        assert owners["dead-peer"] == "me"

    def test_draining_replica_parks_instead_of_firing(self, monkeypatch):
        monkeypatch.setenv("VRPMS_SUB_DEBOUNCE_MS", "0")
        _seed_dataset("subd", 8)
        mgr = subs_mod.manager()
        _, body = mgr.create(_sub_content("subd", 8))
        sid = body["subscriptionId"]
        monkeypatch.setattr(jobs_mod, "is_draining", lambda: True)
        mgr.post_delta(sid, {"add": [3]})
        time.sleep(0.1)
        mgr.run_due()
        doc = mgr.lookup(sid)
        # no generation fired into the draining replica; the pending
        # burst stays durable for whoever adopts the doc
        assert doc["generation"] == 0
        assert doc["pendingCount"] == 1
        row = store.get_database("vrp", None).get_subscription(sid)
        assert row["pending"] == {"add": [3]}

    def test_stats_and_fleet_block(self, monkeypatch):
        monkeypatch.setenv("VRPMS_SUB_DEBOUNCE_MS", "60000")
        _seed_dataset("subs", 8)
        mgr = subs_mod.manager()
        mgr.create(_sub_content("subs", 8))
        _, body = mgr.create(_sub_content("subs", 8))
        mgr.post_delta(body["subscriptionId"], {"add": [3]})
        stats = mgr.stats()
        assert stats["count"] == 2
        assert stats["coalescedBacklog"] == 1
        assert stats["lastGenerationAgeMs"] is None  # nothing fired yet
        info = jobs_mod.replica_info()
        assert info["subs"] == stats

    def test_fleet_block_absent_when_off(self, monkeypatch):
        monkeypatch.setenv("VRPMS_SUBS", "off")
        assert "subs" not in jobs_mod.replica_info()


# ---------------------------------------------------------------------------
# End-to-end: burst coalescing, dedupe, lineage, cadence (slow lane)
# ---------------------------------------------------------------------------


class TestGenerationsE2E:
    def test_burst_coalesces_to_one_generation(self, monkeypatch):
        monkeypatch.setenv("VRPMS_SUB_DEBOUNCE_MS", "150")
        _seed_dataset("sube1", 9)
        mgr = subs_mod.manager()
        _, body = mgr.create(
            _sub_content("sube1", 9, ignoredCustomers=[6, 7, 8])
        )
        sid = body["subscriptionId"]
        launches_before = _metric(
            "vrpms_sub_generations_total", trigger="delta"
        )
        for delta in ({"add": [6]}, {"add": [7]}, {"add": [8]}):
            code, _ = mgr.post_delta(sid, delta)
            assert code == 202
        assert _wait_generation(sid, 1)
        doc = mgr.lookup(sid)
        assert _wait_job_done(doc["lastJobId"])
        time.sleep(0.5)  # nothing else may fire after the burst
        doc = mgr.lookup(sid)
        assert doc["generation"] == 1, doc
        assert doc["pendingCount"] == 0
        assert (
            _metric("vrpms_sub_generations_total", trigger="delta")
            == launches_before + 1
        )
        # the one generation solved the POST-delta world: all of 6,7,8
        rec = store.get_database("vrp", None).get_job(
            doc["lastJobId"], []
        )
        assert rec["status"] == "done", rec
        served = sorted(
            c
            for v in rec["message"]["vehicles"]
            for c in v["tour"][1:-1]
        )
        assert served == list(range(1, 9))

    def test_noop_burst_dedupes_with_zero_launches(self, monkeypatch):
        monkeypatch.setenv("VRPMS_SUB_DEBOUNCE_MS", "100")
        _seed_dataset("sube2", 8)
        mgr = subs_mod.manager()
        _, body = mgr.create(
            _sub_content("sube2", 8, ignoredCustomers=[7])
        )
        sid = body["subscriptionId"]
        mgr.post_delta(sid, {"add": [7]})
        assert _wait_generation(sid, 1)
        assert _wait_job_done(mgr.lookup(sid)["lastJobId"])
        launches = _metric("vrpms_sub_generations_total", trigger="delta")
        coalesced = _metric("vrpms_sub_coalesced_total")
        # add 6 then drop 6: nets to the generation-1 instance exactly
        mgr.post_delta(sid, {"add": [6]})
        mgr.post_delta(sid, {"drop": [6]})
        # one in-window coalesce + one fingerprint-dedupe absorb: wait
        # on the METRIC — claiming the burst zeroes pendingCount before
        # the dedupe decision, so the count alone races the absorb
        assert _wait(
            lambda: _metric("vrpms_sub_coalesced_total") >= coalesced + 2,
            timeout=30,
        )
        doc = mgr.lookup(sid)
        assert doc["generation"] == 1  # ZERO new launches
        assert doc["pendingCount"] == 0
        assert (
            _metric("vrpms_sub_generations_total", trigger="delta")
            == launches
        )
        assert _metric("vrpms_sub_coalesced_total") == coalesced + 2

    def test_delta_posted_mid_launch_is_not_lost(self, monkeypatch):
        # a delta landing while a generation launch is in flight (after
        # the burst is claimed, before the completion path runs) must
        # open a NEW debounce window and fire its own generation — not
        # be silently discarded when the in-flight launch clears state
        monkeypatch.setenv("VRPMS_SUB_DEBOUNCE_MS", "50")
        _seed_dataset("subml", 9)
        mgr = subs_mod.manager()
        _, body = mgr.create(
            _sub_content("subml", 9, ignoredCustomers=[7, 8])
        )
        sid = body["subscriptionId"]
        real_prep = subs_mod.prepare_request
        posted: list = []

        def prep_hook(*a, **k):
            if not posted:
                posted.append(True)
                code, _ = mgr.post_delta(sid, {"add": [8]})
                assert code == 202
            return real_prep(*a, **k)

        monkeypatch.setattr(subs_mod, "prepare_request", prep_hook)
        mgr.post_delta(sid, {"add": [7]})
        assert _wait_generation(sid, 2, timeout=120)
        doc = mgr.lookup(sid)
        assert _wait_job_done(doc["lastJobId"])
        doc = mgr.lookup(sid)
        assert doc["generation"] == 2 and doc["pendingCount"] == 0
        # the second generation solved the mid-launch delta's world
        rec = store.get_database("vrp", None).get_job(
            doc["lastJobId"], []
        )
        served = sorted(
            c
            for v in rec["message"]["vehicles"]
            for c in v["tour"][1:-1]
        )
        assert served == list(range(1, 9))

    def test_lineage_chain_in_records_timeline_and_traces(
        self, monkeypatch
    ):
        monkeypatch.setenv("VRPMS_SUB_DEBOUNCE_MS", "50")
        _seed_dataset("sube3", 9)
        mgr = subs_mod.manager()
        _, body = mgr.create(
            _sub_content("sube3", 9, ignoredCustomers=[7, 8])
        )
        sid = body["subscriptionId"]
        mgr.post_delta(sid, {"add": [7]})
        assert _wait_generation(sid, 1)
        job1 = mgr.lookup(sid)["lastJobId"]
        assert _wait_job_done(job1)
        mgr.post_delta(sid, {"add": [8]})
        assert _wait_generation(sid, 2)
        doc = mgr.lookup(sid)
        job2 = doc["lastJobId"]
        assert _wait_job_done(job2)
        db = store.get_database("vrp", None)
        rec2 = db.get_job(job2, [])
        # the generation seeded from its predecessor, recorded
        assert rec2["resolvedFrom"] == job1
        assert [h["jobId"] for h in doc["lineage"]] == [job1, job2]
        assert [h["trigger"] for h in doc["lineage"]] == ["delta", "delta"]
        assert doc["lineage"][1]["resolvedFrom"] == job1
        # the trace root is the sub.generation span
        trace = spans.ring_get(rec2["traceId"])
        assert trace is not None
        roots = [s for s in trace.spans if s.name == "sub.generation"]
        assert roots and roots[0].attributes["subscriptionId"] == sid
        assert roots[0].attributes["generation"] == 2
        # the timeline narrates the hop fleet-readably
        from service.debug import _lineage_events

        events, hops = _lineage_events(rec2, job2)
        assert hops[0]["jobId"] == job1 and hops[0]["generation"] == 1
        assert "seeded from job " + job1 in events[0]["detail"]
        assert "at cost" in events[0]["detail"]
        # warm-start continuity: generation 2 solved as a seeded
        # continuation of generation 1's result record. The delta
        # CHANGES the customer set, so costs across generations are not
        # comparable — assert the seed mechanism (the resolve counter's
        # "job" source fires exactly when a prior job record seeded the
        # successor), not a cost bound
        assert _metric("vrpms_resolve_total", seed_source="job") >= 1.0
        assert rec2["progress"]["improvements"]

    def test_cadence_resolves_without_deltas(self, monkeypatch):
        _seed_dataset("sube4", 8)
        mgr = subs_mod.manager()
        _, body = mgr.create(
            _sub_content("sube4", 8, resolveEvery=0.3)
        )
        sid = body["subscriptionId"]
        assert _wait_generation(sid, 2, timeout=120)
        doc = mgr.lookup(sid)
        assert all(
            h["trigger"] == "cadence" for h in doc["lineage"]
        ), doc["lineage"]
        # the chain still links: generation 2 seeds from generation 1
        assert doc["lineage"][1]["resolvedFrom"] == doc["lineage"][0][
            "jobId"
        ]
        code, body = mgr.delete(sid)
        assert code == 200


# ---------------------------------------------------------------------------
# SSE stream: per-generation replay + Last-Event-ID (slow lane)
# ---------------------------------------------------------------------------


def _StreamShim(sub_id: str, last_event_id=None):
    """A SubscriptionStreamHandler with the socket plumbing swapped for
    BytesIO — the real _stream/_emit methods, no HTTP."""
    shim = object.__new__(subs_mod.SubscriptionStreamHandler)
    shim.path = f"/api/subscriptions/{sub_id}/stream"
    shim.headers = (
        {} if last_event_id is None
        else {"Last-Event-ID": str(last_event_id)}
    )
    shim.wfile = io.BytesIO()
    shim.send_response = lambda code: None
    shim.send_header = lambda k, v: None
    shim.end_headers = lambda: None
    return shim


def _frames(shim) -> list[dict]:
    out = []
    for chunk in shim.wfile.getvalue().decode().split("\n\n"):
        if not chunk.strip() or chunk.startswith(":"):
            continue
        frame: dict = {}
        for line in chunk.splitlines():
            if line.startswith("event: "):
                frame["event"] = line[len("event: "):]
            elif line.startswith("id: "):
                frame["id"] = line[len("id: "):]
            elif line.startswith("data: "):
                frame["data"] = json.loads(line[len("data: "):])
        out.append(frame)
    return out


class TestStreamSSE:
    def _two_generations(self, monkeypatch, key="subs1"):
        monkeypatch.setenv("VRPMS_SUB_DEBOUNCE_MS", "50")
        _seed_dataset(key, 9)
        mgr = subs_mod.manager()
        _, body = mgr.create(
            _sub_content(key, 9, ignoredCustomers=[7, 8])
        )
        sid = body["subscriptionId"]
        for delta in ({"add": [7]}, {"add": [8]}):
            mgr.post_delta(sid, delta)
            gen = mgr.lookup(sid)["generation"]
            assert _wait_generation(sid, gen + 1)
            assert _wait_job_done(mgr.lookup(sid)["lastJobId"])
        return sid

    def test_replays_every_generation_with_ids(self, monkeypatch):
        sid = self._two_generations(monkeypatch)
        monkeypatch.setenv("VRPMS_STREAM_TIMEOUT_S", "1.0")
        shim = _StreamShim(sid)
        subs_mod.SubscriptionStreamHandler._stream(shim)
        frames = _frames(shim)
        assert frames[0]["event"] == "subscription"
        assert frames[0]["data"]["generation"] == 2
        gens = [f for f in frames if f["event"] == "generation"]
        assert [f["id"] for f in gens] == ["1:end", "2:end"]
        assert all(f["data"]["status"] == "done" for f in gens)
        assert gens[0]["data"]["trigger"] == "delta"
        assert gens[1]["data"]["resolvedFrom"] == gens[0]["data"]["jobId"]
        # terminal frames carry the generation's incumbent
        assert gens[1]["data"]["incumbent"]["bestCost"] is not None
        assert frames[-1]["event"] == "timeout"

    def test_last_event_id_resumes_the_chain(self, monkeypatch):
        sid = self._two_generations(monkeypatch, key="subs2")
        monkeypatch.setenv("VRPMS_STREAM_TIMEOUT_S", "1.0")
        shim = _StreamShim(sid, last_event_id="1:end")
        subs_mod.SubscriptionStreamHandler._stream(shim)
        gens = [
            f for f in _frames(shim) if f["event"] == "generation"
        ]
        assert [f["id"] for f in gens] == ["2:end"]
        # fully caught up: nothing replays, the stream just heartbeats
        shim = _StreamShim(sid, last_event_id="2:end")
        subs_mod.SubscriptionStreamHandler._stream(shim)
        frames = _frames(shim)
        assert [f for f in frames if f["event"] == "generation"] == []
        assert frames[-1]["event"] == "timeout"
        # a mid-generation id replays that generation terminal again
        # (duplicates beat gaps)
        shim = _StreamShim(sid, last_event_id="2:17")
        subs_mod.SubscriptionStreamHandler._stream(shim)
        gens = [
            f for f in _frames(shim) if f["event"] == "generation"
        ]
        assert [f["id"] for f in gens] == ["2:end"]

    def test_unknown_subscription_404s(self):
        shim = _StreamShim("nope")
        subs_mod.SubscriptionStreamHandler._stream(shim)
        assert b'"success": false' in shim.wfile.getvalue().lower()

    def test_non_owner_watcher_polls_bounded_not_spinning(
        self, monkeypatch
    ):
        # a store-only doc (owned by another replica) cannot park on
        # this manager's generation condition: the stream must fall
        # back to a BOUNDED store poll with rate-limited keep-alives,
        # not a flat-out lookup/keep-alive spin until the timeout
        monkeypatch.setenv("VRPMS_STREAM_TIMEOUT_S", "1.5")
        store.get_database("vrp", None).put_subscription(
            "remote-sub",
            {
                "id": "remote-sub",
                "generation": 0,
                "lineage": [],
                "status": "active",
                "replicaId": "some-other-replica",
            },
        )
        shim = _StreamShim("remote-sub")
        subs_mod.SubscriptionStreamHandler._stream(shim)
        frames = _frames(shim)
        assert frames[0]["event"] == "subscription"
        assert frames[-1]["event"] == "timeout"
        beats = [f for f in frames if f["event"] == "keep-alive"]
        # 1.5s of idle non-owner watching: a handful of polls, at most
        # one keep-alive — the un-throttled loop emitted thousands
        assert len(beats) <= 2, len(beats)
        assert len(frames) <= 6, frames


# ---------------------------------------------------------------------------
# Crash/drain handoff: pending state resumes on the adopter (slow lane)
# ---------------------------------------------------------------------------


class TestResumeHandoff:
    def test_pending_delta_survives_manager_death(self, monkeypatch):
        # the "crashed owner" parked a pending burst durably; the
        # adopting manager fires it as a trigger="resume" generation
        monkeypatch.setenv("VRPMS_SUB_DEBOUNCE_MS", "60000")
        _seed_dataset("subr1", 8)
        mgr = subs_mod.manager()
        _, body = mgr.create(
            _sub_content("subr1", 8, ignoredCustomers=[7])
        )
        sid = body["subscriptionId"]
        mgr.post_delta(sid, {"add": [7]})
        subs_mod.reset()  # the owner dies mid-debounce
        resumes = _metric(
            "vrpms_sub_generations_total", trigger="resume"
        )
        mgr = subs_mod.manager()
        mgr.tick()  # the peer's heartbeat sweep adopts + fires
        assert _wait_generation(sid, 1)
        doc = subs_mod.manager().lookup(sid)
        assert _wait_job_done(doc["lastJobId"])
        doc = subs_mod.manager().lookup(sid)
        assert doc["lineage"][0]["trigger"] == "resume"
        assert (
            _metric("vrpms_sub_generations_total", trigger="resume")
            == resumes + 1
        )
        rec = store.get_database("vrp", None).get_job(
            doc["lastJobId"], []
        )
        served = sorted(
            c
            for v in rec["message"]["vehicles"]
            for c in v["tour"][1:-1]
        )
        assert served == list(range(1, 8))  # the delta was not lost

    def test_handoff_preserves_lineage_continuity(self, monkeypatch):
        # generation 1 on the first owner; its pending follow-up delta
        # hands off and the adopter's resume generation still seeds
        # from generation 1's incumbent (resolvedFrom continuity)
        monkeypatch.setenv("VRPMS_SUB_DEBOUNCE_MS", "50")
        _seed_dataset("subr2", 9)
        mgr = subs_mod.manager()
        _, body = mgr.create(
            _sub_content("subr2", 9, ignoredCustomers=[7, 8])
        )
        sid = body["subscriptionId"]
        mgr.post_delta(sid, {"add": [7]})
        assert _wait_generation(sid, 1)
        job1 = mgr.lookup(sid)["lastJobId"]
        assert _wait_job_done(job1)
        monkeypatch.setenv("VRPMS_SUB_DEBOUNCE_MS", "60000")
        mgr.post_delta(sid, {"add": [8]})
        subs_mod.reset()  # drain/crash between the delta and its fire
        mgr = subs_mod.manager()
        mgr.tick()
        assert _wait_generation(sid, 2)
        doc = mgr.lookup(sid)
        assert _wait_job_done(doc["lastJobId"])
        doc = mgr.lookup(sid)
        assert doc["lineage"][1]["trigger"] == "resume"
        rec2 = store.get_database("vrp", None).get_job(
            doc["lastJobId"], []
        )
        assert rec2["resolvedFrom"] == job1


# ---------------------------------------------------------------------------
# VRPMS_SUBS=off: routes 404, responses byte-identical (slow lane)
# ---------------------------------------------------------------------------


class _FakeHandler:
    algorithm = ""
    problem = ""
    _request_id = None
    _trace = None
    _trace_id = None
    _trace_root = None


class TestOffGuard:
    def test_routes_404_when_off(self, monkeypatch):
        monkeypatch.setenv("VRPMS_SUBS", "off")
        srv = serve(port=0)
        port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            import urllib.error
            import urllib.request

            for method, path in (
                ("POST", "/api/subscriptions"),
                ("GET", "/api/subscriptions"),
                ("GET", "/api/subscriptions/x"),
                ("POST", "/api/subscriptions/x/deltas"),
                ("GET", "/api/subscriptions/x/stream"),
                ("DELETE", "/api/subscriptions/x"),
            ):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}{path}",
                    data=b"{}" if method == "POST" else None,
                    method=method,
                )
                with pytest.raises(urllib.error.HTTPError) as e:
                    urllib.request.urlopen(req, timeout=30)
                assert e.value.code == 404, (method, path)
        finally:
            srv.shutdown()

    def test_fixed_seed_job_response_identical_on_and_off(
        self, monkeypatch
    ):
        # the subsystem only ADDS routes: with the switch off (and on,
        # absent any subscription) a fixed-seed async job result must
        # stay byte-identical to the pre-subscription service
        monkeypatch.setenv("VRPMS_CACHE", "off")
        _seed_dataset("suboff", 8)
        results = {}
        for mode in ("on", "off"):
            monkeypatch.setenv("VRPMS_SUBS", mode)
            jobs_mod.shutdown_scheduler()
            errors: list = []
            ctx = jobs_mod._parse_content(
                _sub_content("suboff", 8, seed=5), errors
            )
            assert ctx is not None, errors
            code, body = jobs_mod.submit_headless(ctx)
            assert code == 202, body
            job = jobs_mod.get_live_job(body["jobId"])
            assert job is not None and job.wait(timeout=120)
            assert job.status == "done", job.errors
            results[mode] = json.dumps(job.result, sort_keys=True)
        assert results["on"] == results["off"]
