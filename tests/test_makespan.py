"""Makespan (durationMax) objective weighting.

The reference's VRP result leads with durationMax (reference
api/database.py:72) but nothing ever optimizes it; CostWeights.makespan
prices the longest route's elapsed time into the objective. These tests
pin the ranking semantics, gather/one-hot parity, and the service
plumbing of makespanWeight.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from vrpms_tpu.core import make_instance
from vrpms_tpu.core.cost import (
    CostWeights,
    objective_batch,
    objective_batch_mode,
    objective_hot_batch,
)
from vrpms_tpu.core.encoding import random_giant_batch
from vrpms_tpu.solvers import SAParams, solve_sa


def _ring_instance():
    # symmetric square: unit edges between adjacent corners, sqrt2 across
    pts = np.array([[0, 0], [1, 0], [1, 1], [0, 1], [0.5, 0.5]])
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    return make_instance(d, demands=[0, 1, 1, 1, 1], capacities=[10.0, 10.0])


class TestMakespanObjective:
    def test_prefers_balanced_routes(self):
        inst = _ring_instance()
        # same customer set: one-route-takes-all vs two balanced routes
        lopsided = jnp.asarray([[0, 1, 2, 3, 4, 0, 0]], dtype=jnp.int32)
        balanced = jnp.asarray([[0, 1, 2, 0, 3, 4, 0]], dtype=jnp.int32)
        both = jnp.concatenate([lopsided, balanced])
        plain = CostWeights.make()
        priced = CostWeights.make(makespan=5.0)
        c_plain = np.asarray(objective_batch(both, inst, plain))
        c_priced = np.asarray(objective_batch(both, inst, priced))
        # distance alone may favor the single sweep...
        assert c_plain[0] <= c_plain[1] + 1e-4
        # ...but a priced makespan must flip the preference
        assert c_priced[1] < c_priced[0]

    @pytest.mark.parametrize("tw", [False, True])
    def test_hot_matches_gather_with_makespan(self, rng, tw):
        n = 14
        d = rng.uniform(1, 60, size=(n, n))
        np.fill_diagonal(d, 0)
        kw = {}
        if tw:
            kw = dict(
                ready=np.zeros(n),
                due=rng.uniform(200, 900, n),
                service=np.full(n, 3.0),
            )
        inst = make_instance(
            d, demands=rng.integers(1, 5, n), capacities=[25.0] * 3, **kw
        )
        giants = random_giant_batch(jax.random.key(0), 16, n - 1, 3)
        w = CostWeights.make(makespan=2.0)
        ref = np.asarray(objective_batch(giants, inst, w))
        got = np.asarray(objective_hot_batch(giants, inst, w))
        np.testing.assert_allclose(got, ref, rtol=2e-2)

    def test_pallas_mode_degrades_for_makespan(self, rng):
        # mode 'pallas' with a makespan weight must silently use the XLA
        # path (the kernel computes distance+capacity only)
        d = rng.uniform(1, 60, size=(10, 10))
        inst = make_instance(d, demands=rng.integers(1, 5, 10), capacities=[30.0] * 2)
        giants = random_giant_batch(jax.random.key(1), 128, 9, 2)
        w = CostWeights.make(makespan=1.0)
        a = np.asarray(objective_batch_mode(giants, inst, w, "pallas"))
        b = np.asarray(objective_hot_batch(giants, inst, w))
        np.testing.assert_array_equal(a, b)

    def test_solve_sa_reduces_makespan(self, rng):
        n = 13
        d = rng.uniform(5, 50, size=(n, n))
        np.fill_diagonal(d, 0)
        inst = make_instance(
            d, demands=np.ones(n), capacities=[20.0] * 3
        )
        p = SAParams(n_chains=64, n_iters=1500)
        plain = solve_sa(inst, key=0, params=p)
        priced = solve_sa(
            inst, key=0, params=p, weights=CostWeights.make(makespan=10.0)
        )
        # pricing the longest route must not yield a worse makespan
        assert float(priced.breakdown.duration_max) <= float(
            plain.breakdown.duration_max
        ) + 1e-4


class TestServiceMakespan:
    def test_makespan_weight_accepted_over_http(self):
        import store.memory as mem
        from tests.test_service import post, server, seeded  # noqa: F401

        # reuse the shared fixtures via a local server instance
        mem.reset()
        rng = np.random.default_rng(3)
        pts = rng.uniform(0, 100, size=(6, 2))
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        mem.seed_locations("L", [{"id": i, "demand": 1 if i else 0} for i in range(6)])
        mem.seed_durations("D", d.tolist())
        from service.app import serve
        import threading

        srv = serve(port=0)
        port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            status, resp = post(
                f"http://127.0.0.1:{port}",
                "/api/vrp/sa",
                {
                    "solutionName": "m",
                    "solutionDescription": "d",
                    "locationsKey": "L",
                    "durationsKey": "D",
                    "capacities": [4, 4],
                    "startTimes": [0, 0],
                    "ignoredCustomers": [],
                    "completedCustomers": [],
                    "iterationCount": 400,
                    "makespanWeight": 5.0,
                },
            )
            assert status == 200 and resp["success"]
            msg = resp["message"]
            assert msg["durationMax"] <= msg["durationSum"] + 1e-6
        finally:
            srv.shutdown()
