"""Solve analytics tests: flight records, efficiency rollups, SLO burn
rates, and the regression sentinel (ISSUE 20).

Layers:

  * TestOccupancyMath — padding occupancy vs hand-computed tier pads
    and the tier label spelling;
  * TestPrimalIntegral — the step-integral quality score's arithmetic;
  * TestSloWindows — burn-rate window arithmetic with an injected
    clock (fast window forgets, slow window remembers, budget math);
  * TestExporterUnit — off builds nothing, round trip through the
    store flight seam, bounded queue drops the OLDEST record
    (counted), fail-open on a down store, oversized docs shed the
    profile then drop;
  * TestFlightSeam — the store seam itself: per-(job, replica) upsert,
    bounded memory table, chaos injection;
  * TestSentinel — baseline drift flags once per episode and ticks
    the metric per drifted record;
  * TestSolverByteIdentity — fixed-seed solver results are
    bit-identical with a flight timer installed or absent;
  * TestAnalyticsHTTP (slow) — the debug endpoint end to end:
    off -> 404, a real solve emits a record whose occupancy matches
    the known tier pad, federated rollup across two replica
    identities, local-wins dedupe, store-down degrades (never 500s),
    the timeline's solve-economics event, the fleet `slo` block, and
    a deadline-miss moving the burn-rate gauge.
"""

import json
import threading
import time
import urllib.error
import urllib.request
import uuid

import numpy as np
import pytest

import store
import store.memory as mem
from service import obs as service_obs
from store.faulty import reset_faults
from store.resilient import reset_resilience
from vrpms_tpu.core import tiers
from vrpms_tpu.core.instance import make_instance
from vrpms_tpu.io.synth import synth_cvrp
from vrpms_tpu.obs import analytics, progress, slo, spans
from vrpms_tpu.solvers.sa import SAParams, solve_sa

LADDER = tiers.TierLadder(
    tiers.DEFAULT_N_TIERS, tiers.DEFAULT_V_TIERS, tiers.DEFAULT_T_TIERS
)


def _count(outcome: str) -> float:
    return service_obs.ANALYTICS_TOTAL.labels(outcome=outcome).value


@pytest.fixture(autouse=True)
def clean(monkeypatch):
    monkeypatch.setenv("VRPMS_STORE", "memory")
    monkeypatch.delenv("VRPMS_QUEUE", raising=False)
    monkeypatch.delenv("VRPMS_ANALYTICS", raising=False)
    mem.reset()
    reset_faults()
    reset_resilience()
    analytics.reset_analytics()
    analytics.set_store_factory(None)
    slo.reset_tracker()
    # service.obs wires these at import; later suites must never have
    # left stale observers behind
    analytics.set_observer(
        lambda outcome, n: service_obs.ANALYTICS_TOTAL.labels(
            outcome=outcome
        ).inc(n)
    )
    analytics.set_record_observer(service_obs._record_flight)
    analytics.set_regression_observer(
        lambda metric: service_obs.ANALYTICS_REGRESSIONS.labels(
            metric=metric
        ).inc()
    )
    spans.reset_ring()
    yield
    analytics.reset_analytics()
    analytics.set_store_factory(None)
    slo.reset_tracker()
    mem.reset()
    reset_faults()
    spans.reset_ring()


def _flight_doc(job_id=None, replica="r-local", tier="vrp:16x4x1",
                occ=0.8, **extra):
    doc = {
        "jobId": job_id or uuid.uuid4().hex[:12],
        "replica": replica,
        "problem": "vrp",
        "algorithm": "sa",
        "tier": tier,
        "occupancy": {"n": 0.81, "v": 0.75, "t": 1.0, "compute": occ},
        "deviceS": 0.2,
        "hostS": 0.05,
        "overlapRatio": 0.6,
        "blocks": 4,
        "evals": 1000,
        "evalsPerSec": 5000.0,
        "wallMs": 250.0,
        "gap": 0.1,
        "finishedAt": 1000.0,
    }
    doc.update(extra)
    return doc


def _wait(cond, timeout=10.0, every=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(every)
    return cond()


# ---------------------------------------------------------------------------
# Occupancy math
# ---------------------------------------------------------------------------


class TestOccupancyMath:
    def test_padded_occupancy_matches_hand_computation(self):
        # 13 customers + depot pad to n-tier 16; 3 vehicles to v-tier 4
        inst = synth_cvrp(13, 3, seed=0)
        p = tiers.pad_instance(inst, LADDER)
        assert p.durations.shape[-1] == 16
        occ = tiers.occupancy(p)
        assert occ == {
            "n": round(13 / 16, 4),
            "v": 0.75,
            "t": 1.0,
            "compute": round((13 + 3) / (16 + 4), 4),
        }
        assert tiers.tier_label(p) == "vrp:16x4x1"
        assert tiers.tier_label(p, "tsp") == "tsp:16x4x1"

    def test_unpadded_instance_is_fully_occupied(self):
        inst = synth_cvrp(13, 3, seed=0)
        occ = tiers.occupancy(inst)
        assert occ == {"n": 1.0, "v": 1.0, "t": 1.0, "compute": 1.0}
        assert tiers.tier_label(inst) == "vrp:13x3x1"

    def test_slice_axis_reports_known_t_real(self):
        d = np.ones((8, 10, 10))
        np.einsum("tii->ti", d)[:] = 0.0
        ti = make_instance(d, slice_axis="first")
        p = tiers.pad_instance(ti, LADDER)
        occ = tiers.occupancy(p, t_real=8)
        assert occ["t"] == round(8 / p.durations.shape[0], 4)
        # absent t_real the cyclic-tiled axis reads as fully occupied
        assert tiers.occupancy(p)["t"] == 1.0


# ---------------------------------------------------------------------------
# Primal integral
# ---------------------------------------------------------------------------


class TestPrimalIntegral:
    def test_none_without_profile_or_gaps(self):
        assert analytics.primal_integral(None) is None
        assert analytics.primal_integral({}) is None
        assert analytics.primal_integral(
            {"improvements": [{"wallMs": 5.0, "bestCost": 10.0}]}
        ) is None

    def test_step_integral_hand_case(self):
        profile = {"improvements": [
            {"wallMs": 0.0, "gap": 0.6},
            {"wallMs": 5.0, "gap": 0.2},
            {"wallMs": 10.0, "gap": 0.2},
        ]}
        # 0.6 holds over [0, 5), 0.2 over [5, 10): (3 + 1) / 10
        assert analytics.primal_integral(profile) == 0.4

    def test_first_gap_charged_from_zero(self):
        profile = {"improvements": [
            {"wallMs": 10.0, "gap": 0.5},
            {"wallMs": 20.0, "gap": 0.1},
        ]}
        assert analytics.primal_integral(profile) == 0.5

    def test_single_instant_snapshot_returns_its_gap(self):
        profile = {"improvements": [{"wallMs": 0.0, "gap": 0.3}]}
        assert analytics.primal_integral(profile) == 0.3


# ---------------------------------------------------------------------------
# SLO window arithmetic
# ---------------------------------------------------------------------------


class TestSloWindows:
    def test_burn_rate_budget_math(self, monkeypatch):
        monkeypatch.setenv("VRPMS_SLO_TARGET", "0.9")  # budget 0.1
        now = [1000.0]
        t = slo.SloTracker(clock=lambda: now[0])
        t.note("interactive", True)
        t.note("interactive", False)
        rates = t.burn_rates()["interactive"]
        # 1 miss of 2 = 0.5 miss fraction / 0.1 budget = burn 5.0
        for window in ("fast", "slow"):
            assert rates[window] == {
                "burnRate": 5.0, "total": 2, "met": 1,
            }

    def test_fast_window_forgets_slow_window_remembers(self):
        now = [1000.0]
        t = slo.SloTracker(clock=lambda: now[0])
        t.note("standard", False)
        now[0] += 600.0  # past the 300 s fast window, inside the 1 h
        t.note("standard", True)
        rates = t.burn_rates()["standard"]
        assert rates["fast"]["total"] == 1
        assert rates["fast"]["burnRate"] == 0.0
        assert rates["slow"]["total"] == 2
        assert rates["slow"]["burnRate"] > 0.0

    def test_empty_window_burns_zero_and_absent_class_missing(self):
        now = [1000.0]
        t = slo.SloTracker(clock=lambda: now[0])
        t.note("batch", False)
        now[0] += 7200.0  # everything aged out of both windows
        rates = t.burn_rates()
        assert rates["batch"]["slow"] == {
            "burnRate": 0.0, "total": 0, "met": 0,
        }
        assert "interactive" not in rates

    def test_outcome_cap_bounds_memory(self):
        t = slo.SloTracker(clock=lambda: 1000.0)
        for i in range(slo.MAX_OUTCOMES + 50):
            t.note("standard", True)
        assert len(t._outcomes["standard"]) == slo.MAX_OUTCOMES

    def test_fleet_block_shape(self, monkeypatch):
        monkeypatch.setenv("VRPMS_SLO_TARGET", "0.95")
        slo.note("standard", False)
        block = slo.fleet_block()
        assert block["objective"] == "deadline-met"
        assert block["target"] == 0.95
        assert block["windows"] == {"fast": 300.0, "slow": 3600.0}
        assert block["classes"]["standard"]["fast"]["burnRate"] > 1.0

    def test_module_burn_rates_empty_until_noted(self):
        assert slo.burn_rates() == {}  # reading never builds a tracker


# ---------------------------------------------------------------------------
# Exporter unit layer
# ---------------------------------------------------------------------------


class TestExporterUnit:
    def test_off_by_default_builds_nothing_and_writes_nothing(self):
        analytics.offer(_flight_doc())
        assert analytics._exporter is None
        assert analytics.recent_records() == []
        assert mem._tables["flight_records"] == {}
        assert analytics.queue_depth() == 0  # reading builds nothing

    def test_round_trip_through_store_seam(self, monkeypatch):
        monkeypatch.setenv("VRPMS_ANALYTICS", "on")
        ok0 = _count("ok")
        doc = _flight_doc(job_id="j-round")
        analytics.offer(doc)
        assert analytics.recent_for_job("j-round")["tier"] == "vrp:16x4x1"
        assert analytics.flush(10.0)
        rows = store.get_database("vrp", None).get_flight_records()
        assert len(rows) == 1
        row = rows[0]
        assert row["job_id"] == "j-round"
        assert row["replica"] == "r-local"
        assert row["tier"] == "vrp:16x4x1"
        assert row["algorithm"] == "sa"
        assert row["doc"]["evals"] == 1000
        assert _count("ok") - ok0 == 1

    def test_record_without_job_id_is_not_offered(self, monkeypatch):
        monkeypatch.setenv("VRPMS_ANALYTICS", "on")
        analytics.offer({"tier": "vrp:16x4x1"})
        assert analytics._exporter is None
        assert analytics.recent_records() == []

    def test_queue_overflow_drops_oldest(self, monkeypatch):
        monkeypatch.setenv("VRPMS_ANALYTICS", "on")
        gate = threading.Event()
        written: list = []

        class SlowDB:
            def put_flight_records(self, rows):
                gate.wait(10)
                written.extend(rows)
                return True

        analytics.set_store_factory(lambda: SlowDB())
        dropped0 = _count("dropped")
        exp = analytics.AnalyticsExporter(queue_cap=2, batch=1,
                                          flush_s=0.01)
        try:
            for i in range(5):
                exp.offer(_flight_doc(job_id=f"j{i}"))
            # flusher holds one in flight; cap 2 -> at least 2 dropped
            assert _wait(
                lambda: _count("dropped") - dropped0 >= 2
            ), _count("dropped")
        finally:
            gate.set()
            exp.stop(2.0)
        assert written  # the survivors were still written
        # the newest evidence survived the drop-oldest policy
        assert any(r["job_id"] == "j4" for r in written)

    def test_store_failure_counts_failed_and_never_raises(
        self, monkeypatch
    ):
        monkeypatch.setenv("VRPMS_ANALYTICS", "on")
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        failed0 = _count("failed")
        analytics.offer(_flight_doc(job_id="j-fail"))  # must not raise
        assert analytics.flush(10.0)
        assert _count("failed") - failed0 == 1
        assert analytics.queue_depth() == 0
        # the process-local half survives the outage
        assert analytics.recent_for_job("j-fail") is not None

    def test_oversized_doc_sheds_profile_then_drops(self):
        doc = _flight_doc(profile={
            "improvements": [
                {"wallMs": float(i), "bestCost": 1.0}
                for i in range(4000)
            ],
        })
        row = analytics.serialize_record(doc)
        assert row is not None
        assert "profile" not in row["doc"]
        assert row["doc"]["truncated"] is True
        # a core that is itself too big has nothing left to shed
        big = _flight_doc(tier="x" * (analytics.MAX_ROW_BYTES + 1024))
        assert analytics.serialize_record(big) is None


# ---------------------------------------------------------------------------
# Store flight seam
# ---------------------------------------------------------------------------


class TestFlightSeam:
    def _row(self, job_id, replica, occ=0.8):
        return analytics.serialize_record(
            _flight_doc(job_id=job_id, replica=replica, occ=occ)
        )

    def test_rows_upsert_per_job_and_replica(self):
        db = store.get_database("vrp", None)
        assert db.put_flight_records([self._row("a", "r1")])
        assert db.put_flight_records([self._row("a", "r1", occ=0.9)])
        assert db.put_flight_records([self._row("a", "r2")])
        rows = db.get_flight_records()
        assert len(rows) == 2
        mine = [r for r in rows if r["replica"] == "r1"]
        assert mine[0]["doc"]["occupancy"]["compute"] == 0.9

    def test_empty_batch_is_a_noop_success(self):
        assert store.get_database("vrp", None).put_flight_records([])

    def test_memory_table_stays_bounded(self):
        db = store.get_database("vrp", None)
        cap = mem._InMemoryMixin.MAX_FLIGHT_ROWS
        mem._tables["flight_records"].update({
            (f"j{i}", "a"): {"job_id": f"j{i}", "replica": "a"}
            for i in range(cap)
        })
        db.put_flight_records([self._row("fresh", "a")])
        assert len(mem._tables["flight_records"]) == cap
        assert ("fresh", "a") in mem._tables["flight_records"]

    def test_faulty_plan_injects(self, monkeypatch):
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        db = store.get_database("vrp", None)
        assert db.put_flight_records([self._row("a", "r1")]) is False
        assert db.get_flight_records() is None


# ---------------------------------------------------------------------------
# Regression sentinel
# ---------------------------------------------------------------------------


class TestSentinel:
    BASELINE = {
        "tiers": {"vrp:16x4x1|sa": {"gap": 0.1, "evalsPerSec": 5000.0}},
        "tolerance": {"gap": 0.25, "evalsPerSec": 0.25},
        "minSamples": 2,
    }

    def test_drift_flags_once_per_episode_and_ticks_metric(self):
        reg0 = service_obs.ANALYTICS_REGRESSIONS.labels(
            metric="gap"
        ).value
        s = analytics.RegressionSentinel(baseline=self.BASELINE)
        for _ in range(4):
            s.note(_flight_doc(gap=0.5))  # EWMA pulls far above 0.125
        snap = s.snapshot()
        assert snap["flagged"] == ["vrp:16x4x1|sa:gap"]
        assert snap["baselineKeys"] == ["vrp:16x4x1|sa"]
        # metric ticks per drifted record past min samples
        assert service_obs.ANALYTICS_REGRESSIONS.labels(
            metric="gap"
        ).value - reg0 >= 2
        # recovery clears the episode latch
        for _ in range(30):
            s.note(_flight_doc(gap=0.1))
        assert s.snapshot()["flagged"] == []

    def test_healthy_records_never_flag(self):
        s = analytics.RegressionSentinel(baseline=self.BASELINE)
        for _ in range(10):
            s.note(_flight_doc(gap=0.1, evalsPerSec=5000.0))
        assert s.snapshot()["flagged"] == []

    def test_unknown_key_and_missing_baseline_inert(self):
        s = analytics.RegressionSentinel(baseline=self.BASELINE)
        s.note(_flight_doc(tier="vrp:999x1x1", gap=9.0))
        assert s.snapshot()["flagged"] == []
        inert = analytics.RegressionSentinel(baseline={})
        inert.note(_flight_doc(gap=9.0))
        assert inert.snapshot()["flagged"] == []

    def test_committed_baseline_parses(self):
        with open(analytics.BASELINE_PATH) as f:
            baseline = json.load(f)
        assert baseline["tiers"]
        for entry in baseline["tiers"].values():
            assert set(entry) <= {"gap", "evalsPerSec"}


# ---------------------------------------------------------------------------
# Solver byte identity
# ---------------------------------------------------------------------------


class TestSolverByteIdentity:
    def test_timer_installed_vs_absent_identical(self):
        rng = np.random.default_rng(3)
        pts = rng.uniform(0, 100, size=(10, 2))
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        demands = np.concatenate([[0], rng.uniform(1, 4, size=9)])
        inst = make_instance(d, demands=demands, capacities=[14, 14])
        results = {}
        for mode in ("timed", "bare"):
            timer = analytics.FlightTimer() if mode == "timed" else None
            sink = progress.ProgressSink(job_id=f"bi-{mode}")
            with progress.attach(sink), analytics.flight(timer):
                res = solve_sa(
                    inst, key=0,
                    params=SAParams(n_chains=16, n_iters=900),
                    deadline_s=3600.0,
                )
            results[mode] = (res, sink.snapshot()["bestCost"])
            if timer is not None:
                # the drivers really fed the timer
                assert timer.blocks >= 1
                assert timer.wait_s > 0.0
        timed, bare = results["timed"], results["bare"]
        assert np.array_equal(
            np.asarray(timed[0].giant), np.asarray(bare[0].giant)
        )
        assert float(timed[0].cost) == float(bare[0].cost)
        assert float(timed[0].evals) == float(bare[0].evals)
        assert timed[1] == bare[1]


# ---------------------------------------------------------------------------
# HTTP layer
# ---------------------------------------------------------------------------


def _seed_dataset(key, n, seed=11):
    rng = np.random.default_rng(seed)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        key, [{"id": i, "demand": 2 if i else 0} for i in range(n)]
    )
    mem.seed_durations(key, d.tolist())


def _solve_content(key, n, seed=1, **extra):
    content = {
        "problem": "vrp",
        "algorithm": "sa",
        "solutionName": f"an-{key}-{seed}",
        "solutionDescription": "t",
        "locationsKey": key,
        "durationsKey": key,
        "capacities": [2 * n] * 3,
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": seed,
        "iterationCount": 200,
        "populationSize": 8,
    }
    content.update(extra)
    return content


@pytest.fixture(scope="module")
def server():
    import os

    os.environ["VRPMS_STORE"] = "memory"
    from service import jobs as jobs_mod
    from service.app import serve

    jobs_mod.shutdown_scheduler()
    srv = serve(port=0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    jobs_mod.shutdown_scheduler()


def _post(base, path, body):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=300) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=60) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _poll(base, job_id, timeout=120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status, resp = _get(base, f"/api/jobs/{job_id}")
        assert status == 200, resp
        if resp["job"]["status"] in ("done", "failed", "expired"):
            return resp["job"]
        time.sleep(0.05)
    raise AssertionError(f"job {job_id} never finished")


class TestAnalyticsHTTP:
    @pytest.fixture(autouse=True)
    def env(self, server, monkeypatch):
        from service import jobs as jobs_mod

        monkeypatch.setenv("VRPMS_ANALYTICS", "on")
        _seed_dataset("an7", 7)
        yield
        jobs_mod.shutdown_scheduler()

    def test_endpoint_404s_with_analytics_off(self, server, monkeypatch):
        monkeypatch.setenv("VRPMS_ANALYTICS", "off")
        # the router's plain unrouted 404, byte-identical to the
        # pre-analytics service
        try:
            urllib.request.urlopen(
                server + "/api/debug/analytics", timeout=30
            )
            raise AssertionError("expected a 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
            assert e.read() == b"Not found"

    def test_solve_emits_record_with_known_tier_pad(self, server):
        status, resp = _post(
            server, "/api/vrp/sa", _solve_content("an7", 7)
        )
        assert status == 200, resp
        assert resp["success"] is True
        recs = analytics.recent_records()
        assert recs, "no flight record emitted"
        doc = recs[0]
        # 7 nodes pad to n-tier 8, 3 vehicles to v-tier 4
        assert doc["tier"] == "vrp:8x4x1"
        assert doc["occupancy"] == {
            "n": round(7 / 8, 4),
            "v": 0.75,
            "t": 1.0,
            "compute": round((7 + 3) / (8 + 4), 4),
        }
        assert doc["algorithm"] == "sa"
        assert doc["deviceS"] > 0.0
        assert doc["evals"] > 0
        assert doc["replica"]
        assert doc["cache"] in (
            None, "miss", "exact", "near", "warm", "resolve",
        )
        # durable half: the row reaches the store flight seam
        assert analytics.flush(10.0)
        rows = store.get_database("vrp", None).get_flight_records()
        assert any(r["job_id"] == doc["jobId"] for r in rows)

    def test_off_switch_keeps_fixed_seed_response_byte_identical(
        self, server, monkeypatch
    ):
        monkeypatch.setenv("VRPMS_CACHE", "off")
        responses = {}
        for mode in ("off", "on"):
            monkeypatch.setenv("VRPMS_ANALYTICS", mode)
            status, resp = _post(
                server, "/api/vrp/sa",
                _solve_content("an7", 7, seed=17),
            )
            assert status == 200, resp
            responses[mode] = resp
        on, off = responses["on"], responses["off"]
        # identical payloads modulo the per-request correlation ids
        for r in (on, off):
            r.pop("requestId", None)
            r.pop("traceId", None)
        assert on == off
        # ...and off-mode left no analytics residue anywhere
        monkeypatch.setenv("VRPMS_ANALYTICS", "off")
        analytics.reset_analytics()
        mem._tables["flight_records"].clear()
        status, resp = _post(
            server, "/api/vrp/sa", _solve_content("an7", 7, seed=18)
        )
        assert status == 200, resp
        assert analytics._exporter is None
        assert analytics.recent_records() == []
        assert mem._tables["flight_records"] == {}

    def test_rollup_federates_two_replicas_local_wins(self, server):
        db = store.get_database("vrp", None)
        # a peer's exported rows: one sharing (jobId, replica) with the
        # local ring (stale occupancy — the local doc must win), one
        # only the store knows
        local = _flight_doc(job_id="j-shared", replica="r-here", occ=0.9)
        analytics.offer(local)
        stale = analytics.serialize_record(
            _flight_doc(job_id="j-shared", replica="r-here", occ=0.1)
        )
        peer = analytics.serialize_record(
            _flight_doc(
                job_id="j-peer", replica="peer-1",
                tier="vrp:128x8x1", occ=0.2, gap=0.4,
            )
        )
        assert db.put_flight_records([stale, peer])
        status, resp = _get(server, "/api/debug/analytics")
        assert status == 200, resp
        assert "degraded" not in resp
        rollup = resp["analytics"]
        assert rollup["records"] == 2
        assert sorted(rollup["replicas"]) == ["peer-1", "r-here"]
        by_tier = {t["tier"]: t for t in rollup["tiers"]}
        # worst padding waste ranks first -> the tier-ladder hint
        assert rollup["tiers"][0]["tier"] == "vrp:128x8x1"
        assert rollup["tiers"][0]["paddingWaste"] == 0.8
        assert "hint" in rollup["tiers"][0]
        # local won the (job, replica) conflict: 0.9, not the stale 0.1
        assert by_tier["vrp:16x4x1"]["meanOccupancy"] == 0.9
        assert "hint" not in by_tier["vrp:16x4x1"]
        algos = {a["algorithm"]: a for a in rollup["algorithms"]}
        assert algos["sa"]["solves"] == 2
        assert rollup["pipeline"]["meanOverlapRatio"] == 0.6
        assert resp["sentinel"]["baselineKeys"]
        assert resp["slo"]["objective"] == "deadline-met"

    def test_rollup_store_down_degrades_never_500s(
        self, server, monkeypatch
    ):
        analytics.offer(_flight_doc(job_id="j-local", replica="r-here"))
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        status, resp = _get(server, "/api/debug/analytics")
        assert status == 200, resp
        assert resp["degraded"] is True
        # the local ring still serves the rollup
        assert resp["analytics"]["records"] == 1

    def test_batch_fill_hint_when_launches_run_empty(self, server):
        analytics.offer(_flight_doc(
            job_id="j-b", replica="r-here",
            batch={"members": 1, "padded": 8, "maxBatch": 16,
                   "fill": 0.125},
        ))
        status, resp = _get(server, "/api/debug/analytics")
        assert status == 200, resp
        batch = resp["analytics"]["batch"]
        assert batch["launches"] == 1
        assert batch["meanFill"] == 0.125
        assert "VRPMS_SCHED_WINDOW_MS" in batch["hint"]

    def test_timeline_closes_with_solve_economics(self, server):
        status, resp = _post(
            server, "/api/jobs", _solve_content("an7", 7, seed=5)
        )
        assert status == 202, resp
        job = _poll(server, resp["jobId"])
        assert job["status"] == "done"
        status, resp = _get(server, f"/api/jobs/{job['id']}/timeline")
        assert status == 200, resp
        econ = [
            e for e in resp["timeline"] if e["event"] == "solve.economics"
        ]
        assert len(econ) == 1
        flight = econ[0]["flight"]
        assert flight["jobId"] == job["id"]
        assert flight["tier"] == "vrp:8x4x1"
        assert "solve economics:" in econ[0]["detail"]
        # analytics off: the same surface stays byte-identical to the
        # pre-analytics timeline (no economics event)
        import os

        os.environ["VRPMS_ANALYTICS"] = "off"
        try:
            status, resp = _get(
                server, f"/api/jobs/{job['id']}/timeline"
            )
        finally:
            os.environ["VRPMS_ANALYTICS"] = "on"
        assert status == 200
        assert not [
            e for e in resp["timeline"] if e["event"] == "solve.economics"
        ]

    def test_deadline_miss_moves_burn_rate_and_fleet_slo(self, server):
        # a 5 ms budget cannot cover a real solve: whatever terminal
        # path the job takes (late done / expired / failed) it is a
        # deadline miss for its class
        status, resp = _post(
            server, "/api/jobs",
            _solve_content("an7", 7, seed=9, timeLimit=0.005,
                           qos="interactive"),
        )
        assert status == 202, resp
        _poll(server, resp["jobId"])
        rates = slo.burn_rates()
        assert rates["interactive"]["fast"]["burnRate"] > 0.0
        assert rates["interactive"]["fast"]["total"] >= 1
        # the gauge follows at scrape time
        service_obs.refresh_gauges()
        assert service_obs.SLO_BURN.labels(
            qos="interactive", window="fast"
        ).value > 0.0
        # ...and the fleet rollup serves the same block
        status, resp = _get(server, "/api/debug/fleet")
        assert status == 200, resp
        fleet_slo = resp["fleet"]["slo"]
        assert fleet_slo["objective"] == "deadline-met"
        assert (
            fleet_slo["classes"]["interactive"]["fast"]["burnRate"] > 0.0
        )

    def test_fleet_has_no_slo_block_when_analytics_off(
        self, server, monkeypatch
    ):
        monkeypatch.setenv("VRPMS_ANALYTICS", "off")
        status, resp = _get(server, "/api/debug/fleet")
        assert status == 200, resp
        assert "slo" not in resp["fleet"]
