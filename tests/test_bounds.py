"""Lower-bound certificates validated against the exact oracles.

The whole point of vrpms_tpu.io.bounds is trust: LB <= OPT must hold
ALWAYS (else 'certified' gaps are lies). These tests pin every bound
against brute force on small instances — symmetric, asymmetric,
heterogeneous-fleet, TSP — and sanity-check usefulness (non-vacuous,
1-tree near-tight on Euclidean TSP).
"""

import numpy as np
import pytest

from vrpms_tpu.core import make_instance
from vrpms_tpu.io.bounds import (
    assignment_lb,
    certified_gap_percent,
    cmt_qroute_lb,
    cvrp_forest_lb,
    held_karp_1tree_lb,
    lower_bound,
    mst_lb,
    qroute_lb,
    route_count_lb,
)
from vrpms_tpu.solvers import solve_tsp_bf, solve_vrp_bf


def euclid(rng, n):
    pts = rng.uniform(0, 100, size=(n, 2))
    return np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)


class TestValidity:
    def test_lb_never_exceeds_cvrp_optimum(self, rng):
        for seed in range(4):
            r = np.random.default_rng(seed)
            n = 7
            d = euclid(r, n)
            demands = [0] + [int(x) for x in r.integers(1, 4, n - 1)]
            inst = make_instance(d, demands=demands, capacities=[9.0, 7.0, 5.0])
            opt = float(solve_vrp_bf(inst).cost)
            lb = lower_bound(inst)
            tol = opt * (1 + 1e-5) + 1e-4  # f32 kernel vs f64 bound
            assert 0 < lb <= tol, (seed, lb, opt)
            assert assignment_lb(inst) <= tol
            assert mst_lb(inst) <= tol
            assert cvrp_forest_lb(inst) <= tol
            assert qroute_lb(inst) <= tol
            assert cmt_qroute_lb(inst, iters=5) <= tol
            # the Lagrangian forest bound is the workhorse: near-tight
            # on small Euclidean CVRPs
            assert cvrp_forest_lb(inst) >= 0.75 * opt

    def test_lb_valid_on_asymmetric(self, rng):
        for seed in range(3):
            r = np.random.default_rng(10 + seed)
            n = 7
            d = r.uniform(5, 60, size=(n, n))
            np.fill_diagonal(d, 0)
            demands = [0] + [1] * (n - 1)
            inst = make_instance(d, demands=demands, capacities=[3.0, 3.0, 3.0])
            opt = float(solve_vrp_bf(inst).cost)
            lb = lower_bound(inst)
            assert 0 < lb <= opt * (1 + 1e-5) + 1e-4
            # symmetric-only bounds must return vacuous, not wrong
            assert mst_lb(inst) == 0.0
            assert held_karp_1tree_lb(inst) == 0.0

    def test_one_tree_bounds_tsp_and_is_tight_on_euclidean(self, rng):
        for seed in range(3):
            r = np.random.default_rng(20 + seed)
            n = 8
            inst = make_instance(euclid(r, n), n_vehicles=1)
            opt = float(solve_tsp_bf(inst).cost)
            lb = held_karp_1tree_lb(inst)
            # f32 cost kernel vs f64 bound: allow kernel-rounding slack
            assert lb <= opt * (1 + 1e-5) + 1e-4
            # Held-Karp is known-strong on Euclidean instances
            assert lb >= 0.85 * opt, (seed, lb, opt)
            assert lower_bound(inst) <= opt * (1 + 1e-5) + 1e-4

    def test_time_dependent_bounds_use_slice_minimum(self, rng):
        # TD instances certify against the elementwise cheapest slice:
        # valid (every leg costs at least that) and never above the
        # time-INDEPENDENT optimum of the min-matrix
        r = np.random.default_rng(40)
        n = 7
        base = euclid(r, n)
        factors = np.array([1.0, 1.4, 0.8])
        slices = base[None] * factors[:, None, None]
        demands = [0] + [1] * (n - 1)
        inst = make_instance(
            slices, demands=demands, capacities=[3.0, 3.0, 3.0],
            slice_axis="first",
        )
        lb = lower_bound(inst)
        assert lb > 0  # no longer vacuous
        # the min-matrix instance's exact optimum caps the bound
        inst_min = make_instance(
            slices.min(axis=0), demands=demands, capacities=[3.0, 3.0, 3.0]
        )
        opt_min = float(solve_vrp_bf(inst_min).cost)
        assert lb <= opt_min * (1 + 1e-5) + 1e-4
        # and the true TD optimum is >= the min-matrix optimum >= lb
        opt_td = float(solve_vrp_bf(inst).cost)
        assert lb <= opt_td * (1 + 1e-5) + 1e-4

    def test_certified_gap_is_conservative(self, rng):
        r = np.random.default_rng(30)
        n = 7
        d = euclid(r, n)
        demands = [0] + [1] * (n - 1)
        inst = make_instance(d, demands=demands, capacities=[3.0, 3.0, 3.0])
        res = solve_vrp_bf(inst)
        gap = certified_gap_percent(float(res.cost), inst)
        # the optimum's true gap is 0; the certificate may only
        # overestimate (up to f32 kernel rounding), never go negative
        assert gap is not None and gap >= -1e-3


class TestRouteCount:
    def test_binpacking_lb(self):
        d = np.ones((5, 5))
        np.fill_diagonal(d, 0)
        inst = make_instance(
            d, demands=[0, 3, 3, 3, 3], capacities=[5.0, 5.0, 5.0, 5.0]
        )
        # 12 demand over caps 5+5+5: needs at least 3 vehicles
        assert route_count_lb(inst) == 3
        inst2 = make_instance(
            d, demands=[0, 3, 3, 3, 3], capacities=[12.0, 5.0, 1.0, 1.0]
        )
        assert route_count_lb(inst2) == 1


class TestNgRoute:
    """ng-route relaxation tables (native/ngroute.cpp + io/bounds.py
    wiring): validity against exact optima and a pure-python oracle."""

    def _py_ng(self, d, dem_s, lam, ng_sets, cap_s):
        """Tiny pure-python ng DP twin (exponential-ish; test sizes only)."""
        n = len(dem_s)
        g = ng_sets.shape[1]
        pos_of = [{int(u): p for p, u in enumerate(ng_sets[i]) if u >= 1}
                  for i in range(n)]
        INF = float("inf")
        import itertools

        B = {}
        for i in range(n):
            for M in range(1 << g):
                B[(0, i, M)] = d[i + 1, 0]
        for q in range(1, cap_s + 1):
            for i in range(n):
                for M in range(1 << g):
                    best = INF
                    for j in range(n):
                        if j == i or dem_s[j] > q:
                            continue
                        pj = pos_of[i].get(j + 1)
                        if pj is not None and (M >> pj) & 1:
                            continue
                        Mj = 1 << pos_of[j][j + 1]
                        for p in range(g):
                            if (M >> p) & 1:
                                t = pos_of[j].get(int(ng_sets[i][p]))
                                if t is not None:
                                    Mj |= 1 << t
                        v = d[i + 1, j + 1] + lam[j] + B[(q - dem_s[j], j, Mj)]
                        best = min(best, v)
                    B[(q, i, M)] = best
        R = np.full((cap_s + 1, n), INF)
        rq = np.full(cap_s + 1, INF)
        for q in range(cap_s + 1):
            for i in range(n):
                R[q, i] = B[(q, i, 1 << pos_of[i][i + 1])]
            for j in range(n):
                if dem_s[j] <= q:
                    rq[q] = min(
                        rq[q],
                        d[0, j + 1] + lam[j]
                        + B[(q - dem_s[j], j, 1 << pos_of[j][j + 1])],
                    )
        return rq, R

    def test_native_matches_python_oracle(self, rng):
        from vrpms_tpu.io.bounds import _ng_sets
        from vrpms_tpu.native import ngroute_tables_native

        for seed in range(3):
            r = np.random.default_rng(seed)
            n = 6
            d = euclid(r, n + 1)
            dem = [int(x) for x in r.integers(1, 4, n)]
            lam = r.uniform(-2, 5, n)
            ng = _ng_sets(d, g=3)
            cap = int(sum(dem) // 2 + 2)
            out = ngroute_tables_native(d, dem, lam, ng, cap)
            if out is None:
                pytest.skip("no native toolchain")
            rq_n, R_n = out
            rq_p, R_p = self._py_ng(d, dem, lam, ng, cap)
            rq_n = np.where(rq_n > 1e299, np.inf, rq_n)
            R_n = np.where(R_n > 1e299, np.inf, R_n)
            np.testing.assert_allclose(rq_n, rq_p, rtol=1e-9)
            np.testing.assert_allclose(R_n, R_p, rtol=1e-9)

    def test_ng_sharpened_bound_stays_valid(self, rng):
        # the full ascent (with its final ng evaluation) must never
        # exceed the exact optimum
        for seed in range(3):
            r = np.random.default_rng(seed + 20)
            n = 7
            d = euclid(r, n)
            demands = [0] + [int(x) for x in r.integers(1, 4, n - 1)]
            inst = make_instance(d, demands=demands, capacities=[8.0] * 3)
            opt = float(solve_vrp_bf(inst).cost)
            lb = cmt_qroute_lb(inst, iters=40, ub=opt)
            assert 0 < lb <= opt * (1 + 1e-5) + 1e-4, (seed, lb, opt)
