"""Multi-host (DCN-analog) smoke: the island solver over jax.distributed.

The module docstring of vrpms_tpu.mesh.islands claims multi-host runs
reuse the island code unchanged — `jax.distributed.initialize()` plus a
mesh over all processes' devices makes the ppermute ring cross process
boundaries. This test PROVES it inside CI: two separate OS processes
(2 virtual CPU devices each -> a 4-device global mesh) run
solve_sa_islands and must agree on the champion. On real hardware the
same program spans TPU slices over DCN; here the transport is local,
but the multi-controller code path (global mesh, cross-process
collectives, replicated host inputs) is exactly the one exercised.
"""

import socket
import subprocess
import sys
import textwrap

import jax
import pytest

# the worker subprocesses run solve_sa_islands, which is built on
# jax.shard_map — absent on old-jax containers (see test_islands.py):
# skip instead of failing on an environment limitation
pytestmark = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="jax.shard_map unavailable (old jax); islands need it",
)

WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    # drop any inherited single-process platform pinning
    os.environ.pop("JAX_PLATFORMS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    port, pid, repo = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}", num_processes=2, process_id=pid
    )
    sys.path.insert(0, repo)
    import numpy as np
    from vrpms_tpu.core.encoding import is_valid_giant
    from vrpms_tpu.io.synth import synth_cvrp
    from vrpms_tpu.mesh import IslandParams, make_mesh, solve_sa_islands
    from vrpms_tpu.solvers.sa import SAParams

    mesh = make_mesh()  # all 4 global devices across both processes
    assert jax.device_count() == 4, jax.device_count()
    inst = synth_cvrp(12, 3, seed=1)
    res = solve_sa_islands(
        inst,
        key=0,
        mesh=mesh,
        params=SAParams(n_chains=8, n_iters=60),
        island_params=IslandParams(migrate_every=20, n_migrants=1),
    )
    g = np.asarray(res.giant)
    assert is_valid_giant(g, inst.n_customers, inst.n_vehicles)
    print(f"MULTIHOST_OK {float(res.cost):.3f}", flush=True)

    # the flagship sharded ILS pipeline crosses the process boundary too
    from vrpms_tpu.mesh import solve_ils_islands
    from vrpms_tpu.solvers import ILSParams

    res = solve_ils_islands(
        inst,
        key=0,
        mesh=mesh,
        params=ILSParams.from_budget(2, SAParams(n_chains=8), 40, pool=4),
        island_params=IslandParams(migrate_every=10, n_migrants=1),
    )
    assert is_valid_giant(np.asarray(res.giant), inst.n_customers, inst.n_vehicles)
    print(f"MULTIHOST_ILS_OK {float(res.cost):.3f}", flush=True)

    # Deadline-bounded chunked drivers must take IDENTICAL stop
    # decisions on every controller (mesh.sync.controller_value
    # broadcasts process 0's clock); a per-process local-clock decision
    # here risks one controller issuing ppermute chunks the other never
    # joins — a distributed hang. The tight deadlines make mid-run
    # truncation (the dangerous branch) likely on every CI machine.
    res = solve_sa_islands(
        inst,
        key=0,
        mesh=mesh,
        params=SAParams(n_chains=8, n_iters=400),
        island_params=IslandParams(migrate_every=20, n_migrants=1),
        deadline_s=0.2,
    )
    assert is_valid_giant(np.asarray(res.giant), inst.n_customers, inst.n_vehicles)
    print(f"MULTIHOST_DEADLINE_OK {float(res.cost):.3f}", flush=True)

    res = solve_ils_islands(
        inst,
        key=0,
        mesh=mesh,
        params=ILSParams.from_budget(3, SAParams(n_chains=8), 600, pool=4),
        island_params=IslandParams(migrate_every=10, n_migrants=1),
        deadline_s=0.5,
    )
    assert is_valid_giant(np.asarray(res.giant), inst.n_customers, inst.n_vehicles)
    print(f"MULTIHOST_ILS_DEADLINE_OK {float(res.cost):.3f}", flush=True)
    """
)


def test_island_solve_spans_two_processes(tmp_path):
    with socket.socket() as s:  # a free port for the coordinator
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = {
        "PATH": "/usr/bin:/bin",
        "HOME": "/tmp",
        "PALLAS_AXON_POOL_IPS": "",  # never touch the TPU tunnel here
    }
    import os

    for key in ("PYTHONPATH", "LD_LIBRARY_PATH"):
        if key in os.environ:
            env[key] = os.environ[key]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(port), str(pid), repo],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
            assert p.returncode == 0, out[-2000:]
    finally:
        # a failed/timed-out peer must not leave the other blocked in
        # jax.distributed.initialize, leaking into the test runner
        for p in procs:
            if p.poll() is None:
                p.kill()
    for marker in (
        "MULTIHOST_OK",
        "MULTIHOST_ILS_OK",
        "MULTIHOST_DEADLINE_OK",
        "MULTIHOST_ILS_DEADLINE_OK",
    ):
        costs = []
        for out in outs:
            lines = [
                l for l in out.splitlines() if l.split()[0:1] == [marker]
            ]
            assert lines, (marker, out[-2000:])
            costs.append(float(lines[0].split()[1]))
        # both controllers must agree on the global champion
        assert costs[0] == costs[1], marker
