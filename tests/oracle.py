"""Slow, obviously-correct numpy evaluators used as test oracles.

These mirror the semantics of vrpms_tpu.core.cost with plain Python
loops over decoded routes, so any padded-index/masking bug in the
compiled kernels (the #1 bug farm per SURVEY.md §7) shows up as a
mismatch against these.
"""

from __future__ import annotations

import numpy as np

from vrpms_tpu.core.encoding import routes_from_giant


def naive_eval(giant, inst):
    """Reference evaluation of one giant tour. Returns a dict with the
    same components as cost.CostBreakdown."""
    d = np.asarray(inst.durations)
    demands = np.asarray(inst.demands)
    capacities = np.asarray(inst.capacities)
    ready = np.asarray(inst.ready)
    due = np.asarray(inst.due)
    service = np.asarray(inst.service)
    starts = np.asarray(inst.start_times)
    t_slices = d.shape[0]
    slice_minutes = inst.slice_minutes
    time_dependent = t_slices > 1
    timed = time_dependent or inst.has_tw

    routes = routes_from_giant(giant)
    distance = 0.0
    lateness = 0.0
    cap_excess = 0.0
    route_durations = []
    for r, route in enumerate(routes):
        load = sum(demands[c] for c in route)
        cap_excess += max(0.0, load - capacities[r])
        path = [0] + route + [0]
        if not timed:
            dur = 0.0
            for a, b in zip(path[:-1], path[1:]):
                distance += d[0, a, b]
                dur += d[0, a, b] + service[a]
            route_durations.append(dur)
        else:
            clock = starts[r]
            arrival = clock
            for idx, (a, b) in enumerate(zip(path[:-1], path[1:])):
                depart = clock if idx == 0 else arrival + service[a]
                if time_dependent:
                    s = int(depart // slice_minutes) % t_slices
                else:
                    s = 0
                travel = d[s, a, b]
                distance += travel
                arrival = max(depart + travel, ready[b])
                lateness += max(0.0, arrival - due[b])
            route_durations.append(max(arrival - starts[r], 0.0))
    return {
        "distance": distance,
        "route_durations": np.asarray(route_durations),
        "cap_excess": cap_excess,
        "tw_lateness": lateness,
    }


def naive_greedy_split(perm, inst):
    """Greedy capacity split of a customer order; returns (cost, n_routes).

    Per-vehicle capacities in vehicle-index order (routes past the
    fleet bound reuse the last vehicle's) — the oracle twin of
    core.split._greedy_fresh.
    """
    d = np.asarray(inst.durations)[0]
    demands = np.asarray(inst.demands)
    caps = np.asarray(inst.capacities, dtype=float)
    v = len(caps)
    routes = [[]]
    load = 0.0
    for c in np.asarray(perm):
        c = int(c)
        q = caps[min(len(routes) - 1, v - 1)]
        if load + demands[c] > q and routes[-1]:
            routes.append([])
            load = 0.0
        routes[-1].append(c)
        load += demands[c]
    cost = 0.0
    for route in routes:
        path = [0] + route + [0]
        cost += sum(d[a, b] for a, b in zip(path[:-1], path[1:]))
    return cost, len(routes)


def route_list_cost(routes, inst):
    """Distance of an explicit route list (used to check split decode)."""
    d = np.asarray(inst.durations)[0]
    cost = 0.0
    for route in routes:
        path = [0] + list(route) + [0]
        cost += sum(d[a, b] for a, b in zip(path[:-1], path[1:]))
    return cost
