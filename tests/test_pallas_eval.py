"""Pallas objective kernel: interpret-mode equivalence vs the XLA paths.

The fused kernel (vrpms_tpu/kernels/sa_eval.py) is the TPU hot path of
every SA/GA island sweep; these tests pin its semantics on CPU via
pallas interpret mode (SURVEY.md §4 mesh-without-hardware strategy):
identical selection as the XLA one-hot path — the only rounding is the
bf16 durations matrix (and bf16 demands in the packed column) — for both
the homogeneous-capacity fast path and the general per-vehicle kernel.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from vrpms_tpu.core import make_instance
from vrpms_tpu.core.cost import CostWeights, objective_batch
from vrpms_tpu.core.encoding import random_giant_batch
from vrpms_tpu.kernels.sa_eval import (
    _homogeneous_capacity,
    pallas_available,
    pallas_objective_batch,
)

pytestmark = pytest.mark.skipif(
    not pallas_available(), reason="pallas not importable"
)

W = CostWeights.make()


def _synth(rng, n, caps, demand_lo=1.0, demand_hi=9.0):
    d = rng.uniform(1.0, 100.0, size=(n, n))
    np.fill_diagonal(d, 0.0)
    demands = rng.uniform(demand_lo, demand_hi, size=n)
    return make_instance(d, demands=demands, capacities=caps)


def _check(inst, batch=128, seed=0, rtol=2e-2):
    giants = random_giant_batch(
        jax.random.key(seed), batch, inst.n_customers, inst.n_vehicles
    )
    ref = np.asarray(objective_batch(giants, inst, W))
    got = np.asarray(pallas_objective_batch(giants, inst, W, interpret=True))
    np.testing.assert_allclose(got, ref, rtol=rtol)
    return got


class TestPallasObjective:
    def test_homogeneous_matches_gather(self, rng):
        inst = _synth(rng, 30, [40.0] * 5)
        assert _homogeneous_capacity(inst) == 40.0
        _check(inst)

    def test_heterogeneous_uses_general_kernel(self, rng):
        inst = _synth(rng, 30, [30.0, 50.0, 80.0])
        assert _homogeneous_capacity(inst) is None
        _check(inst)

    def test_negative_demand_uses_general_kernel(self, rng):
        inst = _synth(rng, 12, [40.0, 40.0], demand_lo=-3.0)
        assert _homogeneous_capacity(inst) is None
        _check(inst)

    def test_tsp_uncapacitated(self, rng):
        inst = _synth(rng, 20, None)
        inst = make_instance(np.asarray(inst.durations[0]), n_vehicles=1)
        _check(inst)

    def test_capacity_excess_exact(self):
        # one overloaded route: excess must survive bf16 selection exactly
        d = np.ones((4, 4)) - np.eye(4)
        inst = make_instance(d, demands=[0, 5, 5, 5], capacities=[6.0, 6.0])
        g = jnp.asarray([[0, 1, 2, 3, 0, 0]] * 128, dtype=jnp.int32)
        ref = float(objective_batch(g, inst, W)[0])
        got = float(pallas_objective_batch(g, inst, W, interpret=True)[0])
        assert abs(got - ref) / ref < 1e-3

    def test_transposed_input(self, rng):
        inst = _synth(rng, 16, [35.0] * 3)
        giants = random_giant_batch(jax.random.key(3), 128, 15, 3)
        a = pallas_objective_batch(giants, inst, W, interpret=True)
        b = pallas_objective_batch(
            giants.T, inst, W, transposed=True, interpret=True
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_timed_instances_rejected(self, rng):
        d = rng.uniform(1, 50, size=(8, 8))
        inst = make_instance(
            d, capacities=[99.0], ready=np.zeros(8), due=np.full(8, 1e9)
        )
        giants = random_giant_batch(jax.random.key(4), 128, 7, 1)
        with pytest.raises(ValueError):
            pallas_objective_batch(giants, inst, W, interpret=True)

    def test_batch_must_be_tile_multiple(self, rng):
        inst = _synth(rng, 10, [40.0, 40.0])
        giants = random_giant_batch(jax.random.key(5), 64, 9, 2)
        with pytest.raises(ValueError):
            pallas_objective_batch(giants, inst, W, interpret=True)

    def test_node_count_on_lane_boundary(self, rng):
        # N == 128 forces the padded demand column into a bumped tile
        inst = _synth(rng, 128, [300.0] * 4)
        _check(inst, rtol=2e-2)


class TestDemandScale:
    """bf16-exactness of the packed demand column via gcd scaling
    (ADVICE round 3: unscaled large demands let the kernel rank slightly
    infeasible tours as feasible)."""

    def test_scale_values(self):
        from vrpms_tpu.kernels.sa_eval import demand_scale

        assert demand_scale(np.array([0.0, 3, 7, 250])) == 1.0
        # E-n22-k4 shape: large integers with gcd 100
        assert demand_scale(np.array([0.0, 100, 2500, 1200])) == 100.0
        # irreducible large demands: no exact scaling
        assert demand_scale(np.array([0.0, 257, 1000, 999])) is None
        # non-integral demands: no exact scaling
        assert demand_scale(np.array([0.0, 1.5, 2.25])) is None
        assert demand_scale(np.array([0.0, -1.0, 5.0])) is None

    def test_large_gcd_demands_exact_on_homog_path(self):
        # demands 100x a small integer pattern — bf16 would round them
        # (ulp 16 at 2500); the gcd scaling must keep capacity excess
        # EXACT so near-boundary feasibility never flips
        from vrpms_tpu.kernels.sa_eval import _homogeneous_capacity, demand_scale

        rng = np.random.default_rng(7)
        n = 24
        d = rng.uniform(1.0, 100.0, size=(n, n))
        np.fill_diagonal(d, 0.0)
        demands = np.concatenate([[0], rng.integers(1, 26, size=n - 1)]) * 100.0
        inst = make_instance(d, demands=demands, capacities=[4000.0] * 5)
        assert _homogeneous_capacity(inst) == 4000.0
        assert demand_scale(inst.demands) == 100.0
        giants = random_giant_batch(jax.random.key(2), 128, n - 1, 5)
        from vrpms_tpu.core.cost import _cap_excess_hot, _legs_hot, _rid_batch

        prev_oh, _, _, _ = _legs_hot(giants, inst)
        cape_ref = np.asarray(
            _cap_excess_hot(prev_oh, _rid_batch(giants), inst)
        )
        w0 = CostWeights.make(cap=0.0)
        w1 = CostWeights.make(cap=1.0)
        dist = np.asarray(pallas_objective_batch(giants, inst, w0, interpret=True))
        both = np.asarray(pallas_objective_batch(giants, inst, w1, interpret=True))
        np.testing.assert_allclose(both - dist, cape_ref, rtol=1e-5, atol=1e-3)

    def test_unscalable_demands_fall_back_exact(self):
        # demands with no bf16-exact scaling must take the f32 general
        # kernel and still price excess exactly
        rng = np.random.default_rng(8)
        n = 16
        d = rng.uniform(1.0, 100.0, size=(n, n))
        np.fill_diagonal(d, 0.0)
        demands = np.concatenate([[0], rng.integers(300, 999, size=n - 1)]).astype(
            float
        )
        demands[1] = 257.0  # force gcd 1 with max > 256
        inst = make_instance(d, demands=demands, capacities=[2000.0] * 4)
        from vrpms_tpu.kernels.sa_eval import demand_scale

        assert demand_scale(inst.demands) is None
        giants = random_giant_batch(jax.random.key(3), 128, n - 1, 4)
        from vrpms_tpu.core.cost import _cap_excess_hot, _legs_hot, _rid_batch

        prev_oh, _, _, _ = _legs_hot(giants, inst)
        cape_ref = np.asarray(
            _cap_excess_hot(prev_oh, _rid_batch(giants), inst)
        )
        w0 = CostWeights.make(cap=0.0)
        w1 = CostWeights.make(cap=1.0)
        dist = np.asarray(pallas_objective_batch(giants, inst, w0, interpret=True))
        both = np.asarray(pallas_objective_batch(giants, inst, w1, interpret=True))
        np.testing.assert_allclose(both - dist, cape_ref, rtol=1e-5, atol=1e-3)
