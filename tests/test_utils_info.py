"""Duration query, solve summary, and date helper (reference parity).

Covers the three capabilities the reference's local-test path touches
(reference main.py:1-13): `calculate_duration`-equivalent point queries
with time-of-day slicing (reference src/solver.py:7), the
{tour, total_time, unvisited, date} solve summary (reference
src/solver.py:18-27), and the date stamp format (reference
src/utilities/helper.py:4-6).
"""

import re

import numpy as np
import jax

from vrpms_tpu.core import make_instance, travel_duration
from vrpms_tpu.solvers import SAParams, solve_sa, solve_info
from vrpms_tpu.utils import current_date


class TestTravelDuration:
    def test_time_independent_lookup(self, rng):
        d = rng.uniform(1, 50, size=(6, 6))
        inst = make_instance(d, n_vehicles=2)
        assert float(travel_duration(inst, 1, 4)) == np.float32(d[1, 4])
        # any departure time maps to the single slice
        assert float(travel_duration(inst, 1, 4, 1e4)) == np.float32(d[1, 4])

    def test_time_of_day_slicing(self, rng):
        slices = rng.uniform(1, 50, size=(3, 5, 5))
        inst = make_instance(slices, n_vehicles=1, slice_minutes=60.0)
        # departing inside slice k uses slice k, cyclically
        assert float(travel_duration(inst, 2, 3, 0.0)) == np.float32(slices[0, 2, 3])
        assert float(travel_duration(inst, 2, 3, 61.0)) == np.float32(slices[1, 2, 3])
        assert float(travel_duration(inst, 2, 3, 2 * 60.0)) == np.float32(slices[2, 2, 3])
        assert float(travel_duration(inst, 2, 3, 3 * 60.0)) == np.float32(slices[0, 2, 3])

    def test_jittable_with_traced_args(self, rng):
        d = rng.uniform(1, 50, size=(4, 4))
        inst = make_instance(d, n_vehicles=1)
        f = jax.jit(lambda s, t: travel_duration(inst, s, t))
        assert float(f(1, 2)) == np.float32(d[1, 2])


class TestSolveInfo:
    def test_reference_shape(self, rng):
        d = rng.uniform(1, 50, size=(7, 7))
        inst = make_instance(d, demands=rng.uniform(1, 3, 7), capacities=[20.0, 20.0])
        res = solve_sa(inst, key=0, params=SAParams(n_chains=16, n_iters=200))
        info = solve_info(res, unvisited=[9, 11])
        assert set(info) == {"tour", "total_time", "unvisited", "date"}
        # depot-wrapped flat tour visiting every customer exactly once
        assert info["tour"][0] == 0 and info["tour"][-1] == 0
        visited = [n for n in info["tour"] if n != 0]
        assert sorted(visited) == list(range(1, 7))
        assert info["unvisited"] == [9, 11]
        assert info["total_time"] > 0
        assert re.fullmatch(r"\d{2}-\d{2}-\d{4}", info["date"])


def test_current_date_format():
    assert re.fullmatch(r"\d{2}-\d{2}-\d{4}", current_date())


class TestLoadDotenv:
    """The reference's .env bootstrap (src/__init__.py:1-2, README.md:
    53-66) — same semantics without the python-dotenv dependency."""

    def test_parses_and_never_overrides(self, tmp_path, monkeypatch):
        from vrpms_tpu.utils import load_dotenv

        env = tmp_path / ".env"
        env.write_text(
            "# comment\n"
            "\n"
            "SUPABASE_URL=https://example.supabase.co\n"
            "export SUPABASE_KEY='an on-key'\n"
            'VRPMS_QUOTED="spaced value"\n'
            "VRPMS_PRESET=from-file\n"
            "VRPMS_INLINE=bare-value # inline comment\n"
            'VRPMS_QUOTED_INLINE="a b" # comment after quotes\n'
            'VRPMS_HASH_IN_QUOTES="a # b"\n'
            "not a kv line\n"
        )
        monkeypatch.delenv("SUPABASE_URL", raising=False)
        monkeypatch.delenv("SUPABASE_KEY", raising=False)
        monkeypatch.delenv("VRPMS_QUOTED", raising=False)
        monkeypatch.delenv("VRPMS_INLINE", raising=False)
        monkeypatch.delenv("VRPMS_QUOTED_INLINE", raising=False)
        monkeypatch.delenv("VRPMS_HASH_IN_QUOTES", raising=False)
        monkeypatch.setenv("VRPMS_PRESET", "from-env")
        assert load_dotenv(str(env)) is True
        import os

        assert os.environ["SUPABASE_URL"] == "https://example.supabase.co"
        assert os.environ["SUPABASE_KEY"] == "an on-key"
        assert os.environ["VRPMS_QUOTED"] == "spaced value"
        # inline comments are stripped from unquoted values
        assert os.environ["VRPMS_INLINE"] == "bare-value"
        # ... and from after a quoted value, which still unquotes
        assert os.environ["VRPMS_QUOTED_INLINE"] == "a b"
        # ... but a '#' INSIDE quotes is data
        assert os.environ["VRPMS_HASH_IN_QUOTES"] == "a # b"
        # real environment always beats the file (python-dotenv default)
        assert os.environ["VRPMS_PRESET"] == "from-env"
        monkeypatch.delenv("SUPABASE_URL")
        monkeypatch.delenv("SUPABASE_KEY")
        monkeypatch.delenv("VRPMS_QUOTED")
        monkeypatch.delenv("VRPMS_INLINE")
        monkeypatch.delenv("VRPMS_QUOTED_INLINE")
        monkeypatch.delenv("VRPMS_HASH_IN_QUOTES")

    def test_missing_file_is_fine(self, tmp_path):
        from vrpms_tpu.utils import load_dotenv

        assert load_dotenv(str(tmp_path / "nope.env")) is False

    def test_service_package_bootstraps_dotenv(self):
        # importing the service package runs the loader (reference
        # src/__init__.py:1-2 pattern); it is idempotent, so importing
        # again here simply must not raise
        import importlib

        import service

        importlib.reload(service)
