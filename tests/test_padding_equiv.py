"""Padding-equivalence suite: a tier-padded instance must cost — and for
the deterministic/masked solvers, SOLVE — exactly like its unpadded
original on the real customers.

Kernel level: every evaluation path (gather, one-hot, TW, TD, makespan)
prices a padded tour bit-identically to the real tour it decodes to.
Solver level: SA and GA replay the unpadded trajectory exactly (masked
sampling draws the same values from the same keys), BF enumerates to
the same optimum, and ACO/ILS return valid real tours whose reported
cost re-prices identically on the unpadded instance.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vrpms_tpu.core import tiers
from vrpms_tpu.core.cost import (
    CostWeights,
    evaluate_giant,
    exact_cost,
    objective_batch,
    objective_hot_batch,
    total_cost,
)
from vrpms_tpu.core.encoding import (
    giant_from_routes,
    random_giant_batch,
    routes_from_giant,
)
from vrpms_tpu.core.instance import make_instance
from vrpms_tpu.io.synth import synth_cvrp, synth_vrptw

LADDER = tiers.TierLadder(
    tiers.DEFAULT_N_TIERS, tiers.DEFAULT_V_TIERS, tiers.DEFAULT_T_TIERS
)


def _het(n, v, seed):
    base = synth_cvrp(n, v, seed=seed)
    caps = [20.0 + 10.0 * i for i in range(v)]
    return make_instance(
        np.asarray(base.durations[0]),
        demands=np.asarray(base.demands),
        capacities=caps,
    )


def _td(n, v, seed, t):
    rng = np.random.default_rng(seed)
    d = rng.uniform(5.0, 50.0, size=(t, n, n))
    d[:, 0, 0] = 0.0
    return make_instance(
        d,
        demands=[0.0] + [1.0] * (n - 1),
        capacities=[float(n)] * v,
        slice_axis="first",
    )


def _tw_shifted(n, v, seed):
    """TW instance with NONZERO depot ready and shift starts — the
    regime where a padded tail's surplus separator closes would surface
    in route durations if they were clamped into a real route instead
    of dropped (regression for the rid-clamp segment-sum bug)."""
    rng = np.random.default_rng(seed)
    pts = rng.uniform(0, 100, (n, 2))
    d = np.sqrt(((pts[:, None] - pts[None]) ** 2).sum(-1))
    ready = np.full(n, 480.0)
    due = np.full(n, 2000.0)
    due[0] = 3000.0
    return make_instance(
        d,
        demands=[0.0] + [1.0] * (n - 1),
        capacities=[5.0] * v,
        ready=ready.tolist(),
        due=due.tolist(),
        service=[0.0] + [10.0] * (n - 1),
        start_times=[480.0] * v,
    )


VARIANTS = {
    "capacity": lambda: synth_cvrp(13, 3, seed=1),
    "tw": lambda: synth_vrptw(11, 3, seed=2),
    "tw_shifted": lambda: _tw_shifted(10, 3, seed=7),
    "het_fleet": lambda: _het(12, 3, seed=3),
    "td_factorized": lambda: _td(12, 3, seed=4, t=3),  # rank <= 3: exact
    "td_flat": lambda: _td(10, 2, seed=5, t=5),  # rank 5 > max: flat path
}


@pytest.mark.parametrize("variant", sorted(VARIANTS))
def test_cost_kernels_padding_neutral(variant):
    inst = VARIANTS[variant]()
    p = tiers.pad_instance(inst, LADDER)
    w = CostWeights.make(makespan=0.5)  # makespan priced: route durs too
    gs = random_giant_batch(
        jax.random.key(7), 8, inst.n_customers, inst.n_vehicles
    )
    pg = jnp.stack([tiers.canonical_giant(p, g) for g in gs])

    c_real = np.asarray(objective_batch(gs, inst, w))
    c_pad = np.asarray(objective_batch(pg, p, w))
    np.testing.assert_array_equal(c_real, c_pad)

    h_real = np.asarray(objective_hot_batch(gs, inst, w))
    h_pad = np.asarray(objective_hot_batch(pg, p, w))
    np.testing.assert_allclose(h_real, h_pad, rtol=0, atol=1e-3)

    bd_r = evaluate_giant(gs[0], inst)
    bd_p = evaluate_giant(pg[0], p)
    for field in ("distance", "cap_excess", "tw_lateness"):
        assert float(getattr(bd_r, field)) == float(getattr(bd_p, field))
    assert float(bd_r.duration_max) == float(bd_p.duration_max)
    assert float(bd_r.duration_sum) == float(bd_p.duration_sum)


def test_phantom_is_an_exact_separator():
    """Swapping an interior depot zero for a phantom id (and vice versa
    in the tail) must not move the cost by a single ulp — the invariant
    that makes masked moves over mixed zero/phantom separators sound."""
    inst = synth_cvrp(11, 3, seed=9)
    p = tiers.pad_instance(inst, LADDER)
    w = CostWeights.make(makespan=1.0)
    g = np.asarray(
        tiers.canonical_giant(
            p, random_giant_batch(jax.random.key(1), 1, 10, 3)[0]
        )
    )
    zeros = [
        i
        for i in range(1, int(p.n_real) + int(p.v_real) - 1)
        if g[i] == 0
    ]
    assert zeros
    phantom = int(p.n_real)
    tail = [i for i in range(len(g)) if g[i] == phantom]
    g2 = g.copy()
    g2[zeros[0]], g2[tail[0]] = phantom, 0
    ca = total_cost(evaluate_giant(jnp.asarray(g), p), w)
    cb = total_cost(evaluate_giant(jnp.asarray(g2), p), w)
    assert float(ca) == float(cb)


def _decode_real_cost(res, pinst, inst, w):
    """Strip phantoms, rebuild the REAL giant route-aligned, and price
    it on the unpadded instance."""
    routes = routes_from_giant(res.giant, int(pinst.n_real))
    cust = sorted(c for rt in routes for c in rt)
    assert cust == list(range(1, int(pinst.n_real))), "invalid decode"
    v = int(pinst.v_real)
    aligned = (routes + [[]] * v)[:v]
    assert sorted(c for rt in aligned for c in rt) == cust, (
        "real customers in phantom-vehicle routes"
    )
    real_g = giant_from_routes(aligned, inst.n_customers, inst.n_vehicles)
    return float(exact_cost(real_g, inst, w)[1])


class TestSolverEquivalence:
    def test_sa_exact_trajectory(self):
        from vrpms_tpu.solvers.sa import SAParams, solve_sa

        inst = synth_cvrp(13, 3, seed=5)
        p = tiers.pad_instance(inst, LADDER)
        w = CostWeights.make()
        # explicit temperatures: the auto scale is a masked mean whose
        # f32 reduction order may differ by an ulp across shapes
        sp = SAParams(
            n_chains=32, n_iters=400, t_initial=50.0, t_final=0.5, knn_k=4
        )
        r1 = solve_sa(inst, key=7, params=sp, weights=w, mode="gather")
        r2 = solve_sa(p, key=7, params=sp, weights=w, mode="gather")
        assert float(r1.cost) == float(r2.cost)
        assert _decode_real_cost(r2, p, inst, w) == float(r2.cost)

    def test_sa_tw_exact_trajectory(self):
        from vrpms_tpu.solvers.sa import SAParams, solve_sa

        inst = synth_vrptw(11, 3, seed=6)
        p = tiers.pad_instance(inst, LADDER)
        w = CostWeights.make()
        sp = SAParams(
            n_chains=16, n_iters=300, t_initial=20.0, t_final=0.5, knn_k=4
        )
        r1 = solve_sa(inst, key=3, params=sp, weights=w, mode="gather")
        r2 = solve_sa(p, key=3, params=sp, weights=w, mode="gather")
        assert float(r1.cost) == float(r2.cost)

    def test_sa_tail_invariant(self):
        from vrpms_tpu.solvers.sa import SAParams, solve_sa

        inst = synth_cvrp(13, 3, seed=5)
        p = tiers.pad_instance(inst, LADDER)
        sp = SAParams(n_chains=16, n_iters=200, t_initial=50.0, t_final=0.5)
        res = solve_sa(p, key=1, params=sp, mode="gather")
        g = np.asarray(res.giant)
        lim = int(p.n_real) + int(p.v_real)
        real_pos = [i for i, x in enumerate(g) if 0 < x < int(p.n_real)]
        assert max(real_pos) <= lim - 2

    def test_ga_exact_trajectory(self):
        from vrpms_tpu.solvers.ga import GAParams, solve_ga

        inst = synth_cvrp(13, 3, seed=5)
        p = tiers.pad_instance(inst, LADDER)
        w = CostWeights.make()
        # immigrants off on both sides: the padded path disables them
        # (static ruin shapes can't track the traced real size)
        gp = GAParams(population=32, generations=60, immigrants=0)
        g1 = solve_ga(inst, key=3, params=gp, weights=w, mode="gather")
        g2 = solve_ga(p, key=3, params=gp, weights=w, mode="gather")
        assert float(g1.cost) == float(g2.cost)
        assert _decode_real_cost(g2, p, inst, w) == float(g2.cost)

    def test_ga_het_fleet(self):
        from vrpms_tpu.solvers.ga import GAParams, solve_ga

        inst = _het(11, 3, seed=8)
        p = tiers.pad_instance(inst, LADDER)
        w = CostWeights.make()
        gp = GAParams(population=24, generations=40, immigrants=0)
        g1 = solve_ga(inst, key=2, params=gp, weights=w, mode="gather")
        g2 = solve_ga(p, key=2, params=gp, weights=w, mode="gather")
        assert float(g1.cost) == float(g2.cost)

    def test_bf_same_optimum(self):
        from vrpms_tpu.solvers.bf import solve_vrp_bf

        inst = synth_cvrp(6, 2, seed=2)
        p = tiers.pad_instance(inst, LADDER)
        w = CostWeights.make()
        b1 = solve_vrp_bf(inst, weights=w)
        b2 = solve_vrp_bf(p, weights=w)
        assert float(b1.cost) == float(b2.cost)

    def test_aco_valid_and_consistent(self):
        from vrpms_tpu.solvers.aco import ACOParams, solve_aco

        inst = synth_cvrp(13, 3, seed=5)
        p = tiers.pad_instance(inst, LADDER)
        w = CostWeights.make()
        res = solve_aco(
            p, key=1, params=ACOParams(n_ants=16, n_iters=20), weights=w
        )
        assert _decode_real_cost(res, p, inst, w) == float(res.cost)

    def test_ils_valid_and_consistent(self):
        from vrpms_tpu.solvers.ils import ILSParams, solve_ils
        from vrpms_tpu.solvers.sa import SAParams

        inst = synth_cvrp(13, 3, seed=5)
        p = tiers.pad_instance(inst, LADDER)
        w = CostWeights.make()
        ip = ILSParams(
            rounds=2, sa=SAParams(n_chains=32, n_iters=150), pool=8,
            polish_sweeps=8,
        )
        res = solve_ils(p, key=2, params=ip, weights=w, mode="gather")
        assert _decode_real_cost(res, p, inst, w) == float(res.cost)
        # tail invariant survives ruin-reseed + delta polish
        g = np.asarray(res.giant)
        lim = int(p.n_real) + int(p.v_real)
        real_pos = [i for i, x in enumerate(g) if 0 < x < int(p.n_real)]
        assert max(real_pos) <= lim - 2

    def test_ils_moves_reseed_stays_masked(self):
        """Regression: the 'moves' reseed must confine its perturbation
        to the real prefix — an unmasked clone parks real customers in
        the phantom tail where per-route segment sums drop their legs."""
        from vrpms_tpu.solvers.ils import ILSParams, solve_ils
        from vrpms_tpu.solvers.sa import SAParams

        inst = synth_cvrp(13, 3, seed=5)
        p = tiers.pad_instance(inst, LADDER)
        w = CostWeights.make()
        ip = ILSParams(
            rounds=3, sa=SAParams(n_chains=32, n_iters=100), pool=8,
            polish_sweeps=4, reseed="moves",
        )
        res = solve_ils(p, key=4, params=ip, weights=w, mode="gather")
        assert _decode_real_cost(res, p, inst, w) == float(res.cost)
        g = np.asarray(res.giant)
        lim = int(p.n_real) + int(p.v_real)
        real_pos = [i for i, x in enumerate(g) if 0 < x < int(p.n_real)]
        assert max(real_pos) <= lim - 2

    def test_td_sa_exact_trajectory(self):
        from vrpms_tpu.solvers.sa import SAParams, solve_sa

        inst = _td(10, 2, seed=4, t=3)
        p = tiers.pad_instance(inst, LADDER)
        w = CostWeights.make()
        sp = SAParams(
            n_chains=16, n_iters=150, t_initial=20.0, t_final=0.5, knn_k=4
        )
        r1 = solve_sa(inst, key=2, params=sp, weights=w, mode="gather")
        r2 = solve_sa(p, key=2, params=sp, weights=w, mode="gather")
        assert float(r1.cost) == float(r2.cost)

    def test_warm_start_padded(self):
        from vrpms_tpu.core.split import greedy_split_giant
        from vrpms_tpu.solvers.sa import SAParams, perturbed_clones, solve_sa

        inst = synth_cvrp(12, 3, seed=11)
        p = tiers.pad_instance(inst, LADDER)
        w = CostWeights.make()
        warm = tiers.pad_perm(jnp.arange(1, 12, dtype=jnp.int32), p)
        init = perturbed_clones(
            jax.random.key(1), 16, greedy_split_giant(warm, p), "gather",
            length_real=p.move_limit,
        )
        sp = SAParams(n_chains=16, n_iters=100, t_initial=5.0, t_final=0.5)
        res = solve_sa(
            p, key=1, params=sp, weights=w, init_giants=init, mode="gather"
        )
        assert _decode_real_cost(res, p, inst, w) == float(res.cost)
