"""SA golden tests: near-optimality vs the BF oracle (SURVEY.md §4 item 3)."""

import numpy as np
import jax
import pytest

from vrpms_tpu.core import make_instance
from vrpms_tpu.core.cost import CostWeights, evaluate_giant, total_cost
from vrpms_tpu.core.encoding import is_valid_giant, random_giant_batch
from vrpms_tpu.solvers import solve_tsp_bf, solve_vrp_bf
from vrpms_tpu.solvers.sa import SAParams, solve_sa
from tests.test_core_cost import random_instance


def euclidean_cvrp(rng, n, v, q):
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    demands = np.concatenate([[0], rng.uniform(1, 4, size=n - 1)])
    return make_instance(d, demands=demands, capacities=[q] * v)


class TestSA:
    def test_hits_bf_optimum_tsp(self, rng):
        n = 8
        d = rng.uniform(1, 50, size=(n, n))
        np.fill_diagonal(d, 0)
        inst = make_instance(d, n_vehicles=1)
        opt = float(solve_tsp_bf(inst).cost)
        res = solve_sa(inst, key=0, params=SAParams(n_chains=64, n_iters=3000))
        assert is_valid_giant(res.giant, n - 1, 1)
        assert float(res.cost) <= opt * 1.02 + 1e-3

    def test_near_optimal_cvrp(self, rng):
        inst = euclidean_cvrp(rng, n=8, v=3, q=8)
        opt = float(solve_vrp_bf(inst).cost)
        res = solve_sa(inst, key=1, params=SAParams(n_chains=64, n_iters=4000))
        assert float(res.breakdown.cap_excess) == 0.0
        assert float(res.cost) <= opt * 1.05 + 1e-3

    def test_beats_random_and_respects_feasibility(self, rng):
        inst = euclidean_cvrp(rng, n=20, v=4, q=12)
        w = CostWeights.make()
        rand = random_giant_batch(jax.random.key(9), 64, 19, 4)
        rand_best = min(
            float(total_cost(evaluate_giant(g, inst), w)) for g in rand
        )
        res = solve_sa(inst, key=2, params=SAParams(n_chains=128, n_iters=4000), weights=w)
        assert float(res.cost) < rand_best * 0.8
        assert is_valid_giant(res.giant, 19, 4)
        assert float(res.breakdown.cap_excess) == 0.0

    def test_deterministic_given_key(self, rng):
        inst = euclidean_cvrp(rng, n=10, v=2, q=15)
        p = SAParams(n_chains=32, n_iters=500)
        a = solve_sa(inst, key=5, params=p)
        b = solve_sa(inst, key=5, params=p)
        assert float(a.cost) == float(b.cost)
        assert np.array_equal(np.asarray(a.giant), np.asarray(b.giant))

    def test_tw_instance(self, rng):
        inst = random_instance(rng, n=9, v=2, tw=True)
        res = solve_sa(inst, key=3, params=SAParams(n_chains=32, n_iters=1500))
        assert is_valid_giant(res.giant, 8, 2)

    def test_deadline_truncates_but_returns_valid_best(self, rng):
        inst = euclidean_cvrp(rng, n=15, v=3, q=12)
        # an absurd iteration budget with a ~0 deadline: the solve must
        # stop after its first block and still return a valid solution
        res = solve_sa(
            inst,
            key=4,
            params=SAParams(n_chains=32, n_iters=200_000),
            deadline_s=1e-6,
        )
        assert is_valid_giant(res.giant, 14, 3)
        assert int(res.evals) < 32 * 200_000  # truncated
        assert int(res.evals) >= 32 * 1  # but at least one block ran

    def test_deadline_full_budget_matches_unbounded(self, rng):
        inst = euclidean_cvrp(rng, n=10, v=2, q=15)
        p = SAParams(n_chains=32, n_iters=700)
        free = solve_sa(inst, key=6, params=p)
        timed = solve_sa(inst, key=6, params=p, deadline_s=3600.0)
        # same schedule, same key, deadline never hit: identical result
        assert float(free.cost) == float(timed.cost)
        assert np.array_equal(np.asarray(free.giant), np.asarray(timed.giant))

    def test_pool_returns_sorted_valid_elites(self, rng):
        from vrpms_tpu.core.cost import CostWeights, objective_batch

        inst = euclidean_cvrp(rng, n=12, v=3, q=10)
        res = solve_sa(
            inst, key=7, params=SAParams(n_chains=16, n_iters=500), pool=4
        )
        assert res.pool is not None and res.pool.shape[0] == 4
        assert np.array_equal(np.asarray(res.pool[0]), np.asarray(res.giant))
        costs = np.asarray(objective_batch(res.pool, inst, CostWeights.make()))
        assert (np.diff(costs) >= -1e-4).all()  # best first
        for g in np.asarray(res.pool):
            assert is_valid_giant(g, 11, 3)
        # default: no pool materialised
        res2 = solve_sa(inst, key=7, params=SAParams(n_chains=16, n_iters=500))
        assert res2.pool is None

    def test_nn_init_not_worse_than_random(self, rng):
        inst = euclidean_cvrp(rng, n=25, v=4, q=10)
        budget = SAParams(n_chains=64, n_iters=1000)
        nn = solve_sa(inst, key=1, params=budget)  # init="nn" default
        rnd = solve_sa(
            inst, key=1, params=SAParams(n_chains=64, n_iters=1000, init="random")
        )
        assert is_valid_giant(nn.giant, 24, 4)
        # same budget/seed: constructive seeding should never lose badly
        assert float(nn.cost) <= float(rnd.cost) * 1.02

    def test_initial_giants_shapes_and_validity(self, rng):
        from vrpms_tpu.solvers.sa import initial_giants

        inst = euclidean_cvrp(rng, n=12, v=3, q=10)  # 12 nodes = 11 customers
        for init in ("nn", "random"):
            g = initial_giants(
                jax.random.key(0), 16, inst, SAParams(init=init), "gather"
            )
            assert g.shape == (16, 11 + 3 + 1)
            for row in np.asarray(g):
                assert is_valid_giant(row, 11, 3)
        with pytest.raises(ValueError):
            initial_giants(
                jax.random.key(0), 4, inst, SAParams(init="bogus"), "gather"
            )
