"""Federated live-progress reads (ISSUE 16 acceptance): any-replica
incumbent visibility, SSE relay/reconnect, and watcher-scale caching.

Layers, bottom up: the store owner-lookup seam (get_entry on the
fail-open policy), Replica.owner_of resolution, the staleness-marker
contract (checkpoint-sourced incumbents ALWAYS carry incumbentSource/
staleMs; live overlays NEVER do), store-down degraded reads (marked,
never a 500), the VRPMS_READ_TTL_MS=0 read-through byte-identity guard
(mirroring the depth-memo tests), the SSE id:/Last-Event-ID reconnect
contract, owner relay, and the timeline's checkpoint-lifecycle
narration.
"""

from __future__ import annotations

import io
import json
import threading
import time
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

import store
import store.memory as mem
from service import checkpoint as ckpt_mod
from service import debug as debug_mod
from service import jobs as jobs_mod
from store.base import JobQueueStore
from store.faulty import reset_faults
from store.resilient import reset_resilience
from vrpms_tpu.sched import Replica


@pytest.fixture(autouse=True)
def clean(monkeypatch):
    monkeypatch.setenv("VRPMS_STORE", "memory")
    mem.reset()
    reset_faults()
    reset_resilience()
    ckpt_mod.reset()
    yield
    jobs_mod.shutdown_scheduler()
    ckpt_mod.reset()
    mem.reset()
    reset_faults()
    reset_resilience()


def _save_record(job_id: str, **over) -> dict:
    record = {
        "jobId": job_id,
        "status": "running",
        "problem": "vrp",
        "algorithm": "sa",
        "submittedAt": time.time(),
    }
    record.update(over)
    store.get_database("vrp", None).save_job(job_id, record)
    return record


def _put_ckpt(job_id: str, cost=42.5, block=7, written_ago_s=0.5,
              **over) -> dict:
    state = {
        "problem": "vrp",
        "algorithm": "sa",
        "routes": [[1, 2], [3]],
        "cost": cost,
        "evals": 1000,
        "elapsedMs": 250.0,
        "block": block,
        "writtenAt": time.time() - written_ago_s,
    }
    state.update(over)
    store.get_database("vrp", None).put_checkpoint(job_id, 1, state)
    return state


class _StatusShim:
    """A bare object JobStatusHandler._status can run against."""

    def __init__(self, job_id: str):
        self.path = f"/api/jobs/{job_id}"
        self.headers = {}


def _poll_status(monkeypatch, job_id: str) -> tuple[int, dict]:
    box: dict = {}
    monkeypatch.setattr(
        jobs_mod, "_respond",
        lambda handler, code, body: box.update(code=code, body=body),
    )
    jobs_mod.JobStatusHandler._status(_StatusShim(job_id))
    assert box, "handler never responded"
    return box["code"], box["body"]


# ---------------------------------------------------------------------------
# Owner-lookup seam (store + replica resolution)
# ---------------------------------------------------------------------------


class TestOwnerLookup:
    def test_memory_get_entry_roundtrip(self):
        qs = store.get_queue_store()
        assert qs.get_entry("nope") is None
        qs.enqueue({"id": "e1", "slot": 3, "payload": {"content": {}}})
        entry = qs.get_entry("e1")
        assert entry["state"] == "queued" and entry["lease_owner"] is None
        claimed = qs.claim("r1", lease_s=30.0)
        assert claimed["id"] == "e1"
        entry = qs.get_entry("e1")
        assert entry["state"] == "leased"
        assert entry["lease_owner"] == "r1"
        # a COPY: mutating the returned dict must not corrupt the row
        entry["lease_owner"] = "evil"
        assert qs.get_entry("e1")["lease_owner"] == "r1"

    def test_base_default_predates_the_op(self):
        assert JobQueueStore().get_entry("any") is None

    def _replica(self, rid="reader"):
        return Replica(
            store.get_queue_store(), rid,
            materialize=lambda e: None, submit=lambda j: None,
            complete=lambda *a: None, dead=lambda *a: None,
            lease_s=30.0, poll_s=0.01, heartbeat_s=0.1, reclaim_s=1.0,
            vnodes=4,
        )

    def test_owner_of_resolves_live_lease(self):
        qs = store.get_queue_store()
        qs.enqueue({"id": "e1", "slot": 0, "payload": {"content": {}}})
        rep = self._replica()
        assert rep.owner_of("e1") is None  # queued: nobody owns it
        qs.claim("owner-rep", lease_s=30.0)
        assert rep.owner_of("e1") == "owner-rep"
        assert rep.owner_of("ghost") is None

    def test_owner_of_expired_lease_is_nobody(self):
        qs = store.get_queue_store()
        qs.enqueue({"id": "e1", "slot": 0, "payload": {"content": {}}})
        qs.claim("dead-rep", lease_s=0.01)
        time.sleep(0.05)
        assert self._replica().owner_of("e1") is None

    def test_owner_of_store_down_is_none(self, monkeypatch):
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        monkeypatch.setenv("VRPMS_RESILIENCE", "off")
        rep = self._replica()
        assert rep.owner_of("e1") is None  # never raises


# ---------------------------------------------------------------------------
# Staleness-marker contract (the status poll)
# ---------------------------------------------------------------------------


class TestStalenessContract:
    def test_checkpoint_overlay_always_carries_markers(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "0")
        jid = uuid.uuid4().hex[:12]
        _save_record(jid)
        _put_ckpt(jid, cost=42.5, block=7, written_ago_s=0.5)
        code, body = _poll_status(monkeypatch, jid)
        assert code == 200
        inc = body["job"]["incumbent"]
        assert inc["incumbentSource"] == "checkpoint"
        assert isinstance(inc["staleMs"], int) and inc["staleMs"] >= 400
        assert inc["bestCost"] == 42.5 and inc["block"] == 7
        assert body["job"]["status"] == "running"  # never invented
        assert "degraded" not in body

    def test_rows_predating_written_at_mark_stale_none(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "0")
        jid = uuid.uuid4().hex[:12]
        _save_record(jid)
        state = _put_ckpt(jid)
        del state["writtenAt"]
        store.get_database("vrp", None).put_checkpoint(jid, 1, state)
        code, body = _poll_status(monkeypatch, jid)
        inc = body["job"]["incumbent"]
        # the key is ALWAYS present on a checkpoint-sourced incumbent
        assert inc["incumbentSource"] == "checkpoint"
        assert inc["staleMs"] is None

    def test_terminal_record_never_overlays(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "0")
        jid = uuid.uuid4().hex[:12]
        record = _save_record(jid, status="done")
        _put_ckpt(jid)  # a stale row the terminal delete missed
        code, body = _poll_status(monkeypatch, jid)
        assert body["job"] == record  # byte-identical, no overlay

    def test_relay_off_restores_pre_federation_bytes(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "0")
        jid = uuid.uuid4().hex[:12]
        record = _save_record(jid)
        _put_ckpt(jid)
        monkeypatch.setenv("VRPMS_READ_RELAY", "off")
        code, body = _poll_status(monkeypatch, jid)
        assert body == {"success": True, "job": record}

    def test_local_queue_never_federates(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "local")
        jid = uuid.uuid4().hex[:12]
        record = _save_record(jid)
        _put_ckpt(jid)
        code, body = _poll_status(monkeypatch, jid)
        assert body == {"success": True, "job": record}

    def test_missing_checkpoint_is_not_degraded(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "0")
        jid = uuid.uuid4().hex[:12]
        record = _save_record(jid)
        code, body = _poll_status(monkeypatch, jid)
        # short solves legitimately never checkpoint: bare record, clean
        assert body == {"success": True, "job": record}


# ---------------------------------------------------------------------------
# Store-down degraded reads (marked, never a 500)
# ---------------------------------------------------------------------------


class _CkptDownDB:
    """Record reads work; checkpoint reads are down (the outage window
    where the jobs table answered but solve_checkpoints did not)."""

    degraded = False

    def __init__(self, record):
        self._record = record

    def get_job(self, job_id, errors):
        return self._record

    def get_checkpoint(self, job_id, errors=None):
        if errors is not None:
            errors += [{
                "what": "Database read error", "reason": "ckpt store down",
            }]
        return None


class TestDegradedReads:
    def test_ckpt_store_down_marks_degraded_200(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "0")
        jid = uuid.uuid4().hex[:12]
        record = _save_record(jid)
        monkeypatch.setattr(
            jobs_mod.store, "get_database",
            lambda *a, **kw: _CkptDownDB(record),
        )
        code, body = _poll_status(monkeypatch, jid)
        assert code == 200  # degraded, never a 500
        assert body["degraded"] is True
        assert "incumbent" not in body["job"]  # no invented state

    def test_checkpoint_incumbent_reports_outage(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "0")
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        monkeypatch.setenv("VRPMS_RESILIENCE", "off")
        snap, degraded = jobs_mod._checkpoint_incumbent("j1")
        assert snap is None and degraded is True

    def test_checkpoint_miss_is_clean(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "0")
        snap, degraded = jobs_mod._checkpoint_incumbent("j-none")
        assert snap is None and degraded is False


# ---------------------------------------------------------------------------
# Watcher-scale read cache (the depth-memo guard, generalized)
# ---------------------------------------------------------------------------


class _CountingDB:
    degraded = False

    def __init__(self, record):
        self.calls = 0
        self._record = record

    def get_job(self, job_id, errors):
        self.calls += 1
        return self._record


class TestReadCache:
    def test_n_watchers_cost_one_read_per_ttl(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "60000")
        monkeypatch.setenv("VRPMS_READ_RELAY", "off")
        jid = uuid.uuid4().hex[:12]
        db = _CountingDB(_save_record(jid, status="done"))
        monkeypatch.setattr(
            jobs_mod.store, "get_database", lambda *a, **kw: db
        )
        first = _poll_status(monkeypatch, jid)
        for _ in range(63):
            assert _poll_status(monkeypatch, jid) == first
        assert db.calls == 1  # 64 watchers, ONE store round trip

    def test_ttl_zero_reads_through_byte_identically(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_READ_RELAY", "off")
        jid = uuid.uuid4().hex[:12]
        db = _CountingDB(_save_record(jid, status="done"))
        monkeypatch.setattr(
            jobs_mod.store, "get_database", lambda *a, **kw: db
        )
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "60000")
        cached = [
            json.dumps(_poll_status(monkeypatch, jid), sort_keys=True)
            for _ in range(3)
        ]
        jobs_mod.shutdown_scheduler()  # clears the cache between arms
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "0")
        through = [
            json.dumps(_poll_status(monkeypatch, jid), sort_keys=True)
            for _ in range(3)
        ]
        assert cached == through  # the cache changes cost, not bytes
        assert db.calls == 1 + 3  # one cached read + three read-through

    def test_local_mode_never_caches(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "local")
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "60000")
        jid = uuid.uuid4().hex[:12]
        db = _CountingDB(_save_record(jid, status="done"))
        monkeypatch.setattr(
            jobs_mod.store, "get_database", lambda *a, **kw: db
        )
        for _ in range(4):
            _poll_status(monkeypatch, jid)
        assert db.calls == 4

    def test_cache_is_bounded(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "60000")
        for i in range(jobs_mod._READ_CACHE_CAP + 50):
            jobs_mod._cached_read(f"job:bounded-{i}", lambda: {"i": 1})
        with jobs_mod._read_lock:
            assert len(jobs_mod._read_cache) <= jobs_mod._READ_CACHE_CAP

    def test_errors_are_never_cached(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "60000")
        calls = {"n": 0}

        def boom():
            calls["n"] += 1
            raise RuntimeError("down")

        for _ in range(3):
            with pytest.raises(RuntimeError):
                jobs_mod._cached_read("job:boom", boom)
        assert calls["n"] == 3  # an outage is retried, not memoized


# ---------------------------------------------------------------------------
# SSE: id fields, Last-Event-ID reconnect, federated follow
# ---------------------------------------------------------------------------


def _StreamShim(job_id: str, last_event_id=None):
    """A JobStreamHandler with the socket plumbing swapped for BytesIO —
    the real _follow_record/_federated_snap/_emit methods, no HTTP."""
    shim = object.__new__(jobs_mod.JobStreamHandler)
    shim.path = f"/api/jobs/{job_id}/stream"
    shim.headers = (
        {} if last_event_id is None
        else {"Last-Event-ID": str(last_event_id)}
    )
    shim.wfile = io.BytesIO()
    return shim


def _frames(shim) -> list[dict]:
    """Parse captured SSE bytes into [{event, id?, data}] frames."""
    out = []
    for chunk in shim.wfile.getvalue().decode().split("\n\n"):
        if not chunk.strip() or chunk.startswith(":"):
            continue
        frame: dict = {}
        for line in chunk.splitlines():
            if line.startswith("event: "):
                frame["event"] = line[len("event: "):]
            elif line.startswith("id: "):
                frame["id"] = line[len("id: "):]
            elif line.startswith("data: "):
                frame["data"] = json.loads(line[len("data: "):])
        out.append(frame)
    return out


class TestSSEReconnect:
    def test_progress_events_carry_ids(self):
        jid = uuid.uuid4().hex[:12]
        record = _save_record(
            jid, status="done", incumbent={"block": 5, "bestCost": 9.0}
        )
        shim = _StreamShim(jid)
        jobs_mod.JobStreamHandler._follow_record(shim, jid, record, None)
        frames = _frames(shim)
        assert [f["event"] for f in frames] == ["progress", "done"]
        assert frames[0]["id"] == "5"

    def test_last_event_id_suppresses_the_seen_block(self):
        jid = uuid.uuid4().hex[:12]
        record = _save_record(
            jid, status="done", incumbent={"block": 5, "bestCost": 9.0}
        )
        shim = _StreamShim(jid)
        jobs_mod.JobStreamHandler._follow_record(shim, jid, record, 5)
        frames = _frames(shim)
        # the reconnecting watcher already saw block 5: straight to done
        assert [f["event"] for f in frames] == ["done"]

    def test_resumed_attempt_block_zero_streams_again(self):
        # blocks restart at 0 on a resumed attempt: `!=` (not `>`) must
        # let the new attempt's block 0 through a watcher who saw 5
        jid = uuid.uuid4().hex[:12]
        record = _save_record(
            jid, status="done",
            incumbent={"block": 0, "bestCost": 7.0, "resumed": True},
        )
        shim = _StreamShim(jid)
        jobs_mod.JobStreamHandler._follow_record(shim, jid, record, 5)
        frames = _frames(shim)
        assert [f["event"] for f in frames] == ["progress", "done"]
        assert frames[0]["id"] == "0"

    def test_reconnect_over_http(self, monkeypatch):
        """The end-to-end contract: drop, reconnect with Last-Event-ID
        (as onto any replica), and the seen incumbent is not replayed."""
        from service.app import serve

        jobs_mod.shutdown_scheduler()
        srv = serve(port=0)
        port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            jid = uuid.uuid4().hex[:12]
            _save_record(
                jid, status="done", incumbent={"block": 3, "bestCost": 1.0}
            )
            url = f"http://127.0.0.1:{port}/api/jobs/{jid}/stream"
            with urllib.request.urlopen(url, timeout=30) as resp:
                first = resp.read().decode()
            assert "id: 3" in first and "event: progress" in first
            req = urllib.request.Request(
                url, headers={"Last-Event-ID": "3"}
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                second = resp.read().decode()
            assert "event: progress" not in second
            assert "event: done" in second
        finally:
            srv.shutdown()
            jobs_mod.shutdown_scheduler()

    def test_follow_record_federates_checkpoint_snaps(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "0")
        monkeypatch.setenv("VRPMS_CKPT_MS", "20")
        monkeypatch.setenv("VRPMS_STREAM_TIMEOUT_S", "0.4")
        jid = uuid.uuid4().hex[:12]
        record = _save_record(jid)  # running on "another replica"
        _put_ckpt(jid, cost=42.5, block=7)
        shim = _StreamShim(jid)
        jobs_mod.JobStreamHandler._follow_record(shim, jid, record, None)
        frames = _frames(shim)
        progress = [f for f in frames if f["event"] == "progress"]
        assert progress, frames
        assert progress[0]["data"]["incumbentSource"] == "checkpoint"
        assert "staleMs" in progress[0]["data"]
        assert frames[-1]["event"] == "timeout"  # never invented failed


# ---------------------------------------------------------------------------
# Owner relay
# ---------------------------------------------------------------------------


class _OwnerStub:
    """Stands in for jobs_mod._replica on the reader side."""

    def __init__(self, owner, addr):
        self._owner = owner
        self.store = self
        self._addr = addr

    def owner_of(self, job_id):
        return self._owner

    def replica_infos(self):
        return {self._owner: {"addr": self._addr}}


def _relay_server(payload: dict):
    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            body = json.dumps(payload).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


class TestRelay:
    def test_relay_marks_and_rides_the_owner_view(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "0")
        monkeypatch.setenv("VRPMS_REPLICA_ID", "reader")
        srv = _relay_server({
            "success": True,
            "job": {"incumbent": {"block": 9, "bestCost": 5.5}},
        })
        try:
            addr = f"127.0.0.1:{srv.server_address[1]}"
            monkeypatch.setattr(
                jobs_mod, "_replica", _OwnerStub("owner", addr)
            )
            snap = jobs_mod._relay_snap("j1")
            assert snap["incumbentSource"] == "relay"
            assert snap["bestCost"] == 5.5 and snap["block"] == 9
            assert snap["staleMs"] >= 0
        finally:
            srv.shutdown()
            jobs_mod._replica = None  # the stub must not reach drain

    def test_second_hand_state_is_never_rerelayed(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "0")
        monkeypatch.setenv("VRPMS_REPLICA_ID", "reader")
        srv = _relay_server({
            "success": True,
            "job": {"incumbent": {
                "block": 9, "bestCost": 5.5,
                "incumbentSource": "checkpoint", "staleMs": 100,
            }},
        })
        try:
            addr = f"127.0.0.1:{srv.server_address[1]}"
            monkeypatch.setattr(
                jobs_mod, "_replica", _OwnerStub("owner", addr)
            )
            assert jobs_mod._relay_snap("j1") is None
        finally:
            srv.shutdown()
            jobs_mod._replica = None  # the stub must not reach drain

    def test_self_or_gone_owner_falls_back(self, monkeypatch):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_READ_TTL_MS", "0")
        monkeypatch.setenv("VRPMS_REPLICA_ID", "reader")
        # the owner is THIS replica: a relay to self would be a loop
        monkeypatch.setattr(
            jobs_mod, "_replica", _OwnerStub("reader", "127.0.0.1:1")
        )
        assert jobs_mod._relay_snap("j1") is None
        # owner unreachable: None, the caller degrades to checkpoint
        monkeypatch.setattr(
            jobs_mod, "_replica", _OwnerStub("owner", "127.0.0.1:1")
        )
        assert jobs_mod._relay_snap("j1") is None
        jobs_mod._replica = None  # the stub must not reach drain


# ---------------------------------------------------------------------------
# Fleet checkpoint health + timeline narration (the debug satellites)
# ---------------------------------------------------------------------------


class TestFleetCkptHealth:
    def test_replica_info_carries_ckpt_health(self):
        info = jobs_mod.replica_info()
        ck = info["ckpt"]
        assert set(ck) >= {
            "entries", "lastFlushAgeMs", "written", "resumed", "dropped",
        }
        assert ck["entries"] == 0 and ck["lastFlushAgeMs"] is None

    def test_health_tracks_flush_age(self):
        ckpt = ckpt_mod.checkpointer()
        with ckpt._lock:
            ckpt._last_write = time.time() - 1.0
        age = ckpt.health()["lastFlushAgeMs"]
        assert age is not None and age >= 900


class TestTimelineNarration:
    @staticmethod
    def _merged(spans):
        return {"spans": spans, "replicas": [], "startedAt": 0.0}

    def test_ckpt_write_and_resume_events(self):
        events = debug_mod._span_events(self._merged([
            {
                "name": "ckpt.write", "startMs": 10.0, "durationMs": 2.0,
                "replica": "r1",
                "attributes": {"attempt": 1, "cost": 42.5},
            },
            {
                "name": "ckpt.resume", "startMs": 20.0, "durationMs": 0.0,
                "replica": "r2",
                "attributes": {"source": "reclaim", "cost": 42.5},
            },
        ]))
        kinds = [e["event"] for e in events]
        assert kinds == ["ckpt.write", "ckpt.resume"]
        assert "checkpoint written" in events[0]["detail"]
        assert "cost 42.5" in events[0]["detail"]
        assert "resumed from checkpoint (reclaim" in events[1]["detail"]
        assert events[1]["source"] == "reclaim"

    def test_drain_resume_narrates_the_nack(self):
        events = debug_mod._span_events(self._merged([{
            "name": "ckpt.resume", "startMs": 30.0, "durationMs": 0.0,
            "replica": "r2", "attributes": {"source": "drain"},
        }]))
        kinds = [e["event"] for e in events]
        assert kinds == ["drain.nack", "ckpt.resume"]
        assert "nacked it back to the shared queue" in events[0]["detail"]
