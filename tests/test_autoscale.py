"""Elastic-fleet autoscaling tests (ISSUE 18).

Layers:

  * TestControllerMath — pure policy units: backlog pricing by class,
    the QoS-feasible minimum, min/max clamps, immediate scale-up vs
    cooldown-gated scale-down, hysteresis at the capacity boundary,
    and the fail-open degraded freeze;
  * TestChurnGeometry — ring-churn properties over randomized
    memberships: single-member churn moves EXACTLY the lost member's
    share (~1/N) and nothing else, the exact arc-walk agrees with
    random probing, and inherited_tokens matches brute-force owner
    checks token by token;
  * TestVictimSelection — scale-in victim by claim-mix overlap:
    survivors' warm tiers decide, idle replicas are free wins, the
    last replica is never drained, draining replicas never re-picked;
  * TestStaleSplit — heartbeat-registry hygiene: docs older than the
    lease window are stale, absence of evidence stays live;
  * TestChurnWarmTick — the heartbeat churn watcher: membership change
    launches a background warmup of EXACTLY the inherited tier-ladder
    shapes (asserted against the ring diff), first sight and no-change
    ticks launch nothing;
  * TestFleetHTTP — the HTTP surface under VRPMS_QUEUE=store: the
    autoscale block on /api/debug/fleet, stale marking + live count,
    the chaos contract (VRPMS_STORE=faulty freezes the last-known
    recommendation marked degraded and the fleet endpoint NEVER 500s),
    scale-in status codes (409 solo, 404 unknown, 502 unreachable,
    202 self-drain), and drain idempotency (second POST reports
    alreadyDraining, no second drain thread);
  * TestAutoscaleOff — VRPMS_AUTOSCALE=off removes everything: no
    fleet keys, scalein 404s, fixed-seed solves byte-identical on/off.
"""

import json
import time
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import store
import store.memory as mem
from store.faulty import reset_faults
from service import autoscale as autoscale_mod
from service import jobs as jobs_mod
from vrpms_tpu.sched import autoscale as policy
from vrpms_tpu.sched.ring import SLOTS, HashRing, slot


@pytest.fixture(autouse=True)
def clean_store(monkeypatch):
    monkeypatch.setenv("VRPMS_STORE", "memory")
    monkeypatch.delenv("VRPMS_QUEUE", raising=False)
    monkeypatch.delenv("VRPMS_AUTOSCALE", raising=False)
    mem.reset()
    reset_faults()
    autoscale_mod.reset()
    yield
    mem.reset()
    reset_faults()
    autoscale_mod.reset()


# ---------------------------------------------------------------------------
# Controller math
# ---------------------------------------------------------------------------


class TestControllerMath:
    def test_work_seconds_prices_classes(self):
        # no split: whole depth at the class-agnostic EWMA
        assert policy.work_seconds(10, None, None, 2.0) == pytest.approx(20.0)
        # split priced per class
        w = policy.work_seconds(
            10,
            {"interactive": 4, "batch": 6},
            {"interactive": 0.5, "batch": 3.0},
            2.0,
        )
        assert w == pytest.approx(4 * 0.5 + 6 * 3.0)
        # jobs the split missed price at the class-agnostic rate
        w = policy.work_seconds(
            12, {"interactive": 4}, {"interactive": 0.5}, 2.0
        )
        assert w == pytest.approx(4 * 0.5 + (12 - 4) * 2.0)
        # a class missing from the seconds map falls back too
        w = policy.work_seconds(5, {"standard": 5}, {}, 1.5)
        assert w == pytest.approx(5 * 1.5)

    def test_required_replicas_is_feasible_minimum(self):
        assert policy.required_replicas(0.0, 30.0, 2) == 1
        assert policy.required_replicas(100.0, 10.0, 2) == 5
        assert policy.required_replicas(101.0, 10.0, 2) == 6
        # per-replica concurrency scales capacity linearly
        assert policy.required_replicas(100.0, 10.0, 10) == 1

    def _inputs(self, depth, per=1):
        return {"depth": depth, "jobSeconds": 1.0, "perReplica": per,
                "members": 1}

    def test_clamps_min_max(self, monkeypatch):
        monkeypatch.setenv("VRPMS_AUTOSCALE_MIN", "2")
        monkeypatch.setenv("VRPMS_AUTOSCALE_MAX", "3")
        monkeypatch.setenv("VRPMS_AUTOSCALE_HEADROOM_S", "10")
        ctl = policy.Controller()
        rec = ctl.observe(self._inputs(0), now=0.0)
        assert rec["desired"] == 2  # floor
        rec = ctl.observe(self._inputs(1000), now=1.0)
        assert rec["desired"] == 3  # cap

    def test_up_immediate_down_waits_cooldown(self, monkeypatch):
        monkeypatch.setenv("VRPMS_AUTOSCALE_HEADROOM_S", "10")
        monkeypatch.setenv("VRPMS_AUTOSCALE_COOLDOWN_S", "5")
        monkeypatch.setenv("VRPMS_AUTOSCALE_HYSTERESIS", "0")
        ctl = policy.Controller()
        assert ctl.observe(self._inputs(0), now=0.0)["decision"] == "init"
        rec = ctl.observe(self._inputs(100), now=1.0)
        assert rec["decision"] == "up" and rec["desired"] == 10
        # backlog gone: the down-signal must AGE before it applies
        rec = ctl.observe(self._inputs(0), now=2.0)
        assert rec["decision"] == "cooldown" and rec["desired"] == 10
        assert 0 < rec["cooldownRemaining"] <= 5
        rec = ctl.observe(self._inputs(0), now=6.9)
        assert rec["decision"] == "cooldown" and rec["desired"] == 10
        rec = ctl.observe(self._inputs(0), now=7.1)
        assert rec["decision"] == "down" and rec["desired"] == 1

    def test_up_during_cooldown_cancels_down_signal(self, monkeypatch):
        monkeypatch.setenv("VRPMS_AUTOSCALE_HEADROOM_S", "10")
        monkeypatch.setenv("VRPMS_AUTOSCALE_COOLDOWN_S", "5")
        monkeypatch.setenv("VRPMS_AUTOSCALE_HYSTERESIS", "0")
        ctl = policy.Controller()
        ctl.observe(self._inputs(100), now=0.0)
        ctl.observe(self._inputs(0), now=1.0)  # down-signal starts aging
        rec = ctl.observe(self._inputs(200), now=2.0)
        assert rec["decision"] == "up" and rec["desired"] == 20
        # the old down-signal must not fire stale after the burst
        rec = ctl.observe(self._inputs(0), now=6.5)
        assert rec["decision"] == "cooldown" and rec["desired"] == 20

    def test_hysteresis_blocks_marginal_down(self, monkeypatch):
        monkeypatch.setenv("VRPMS_AUTOSCALE_HEADROOM_S", "10")
        monkeypatch.setenv("VRPMS_AUTOSCALE_COOLDOWN_S", "0")
        monkeypatch.setenv("VRPMS_AUTOSCALE_HYSTERESIS", "0.25")
        ctl = policy.Controller()
        ctl.observe(self._inputs(15), now=0.0)  # raw 2
        assert ctl.desired() == 2
        # raw says 1, but 9s of work > 75% of one replica's 10s
        # capacity: a wiggle would re-raise the signal — hold
        rec = ctl.observe(self._inputs(9), now=1.0)
        assert rec["decision"] == "hold" and rec["desired"] == 2
        # comfortably inside the smaller fleet: down (cooldown 0)
        rec = ctl.observe(self._inputs(6), now=2.0)
        assert rec["decision"] == "down" and rec["desired"] == 1

    def test_degraded_freezes_last_recommendation(self, monkeypatch):
        monkeypatch.setenv("VRPMS_AUTOSCALE_HEADROOM_S", "10")
        ctl = policy.Controller()
        ctl.observe(self._inputs(30), now=0.0)
        assert ctl.desired() == 3
        rec = ctl.observe(None, now=1.0)
        assert rec["decision"] == "frozen"
        assert rec["degraded"] is True
        assert rec["desired"] == 3  # frozen, not guessed
        assert ctl.desired() == 3
        # recovery clears the flag without losing cooldown safety
        rec = ctl.observe(self._inputs(30), now=2.0)
        assert rec["degraded"] is False and rec["desired"] == 3

    def test_blind_bootstrap_serves_one(self):
        ctl = policy.Controller()
        assert ctl.desired() == 1  # before any observation
        rec = ctl.observe(None, now=0.0)
        assert rec["desired"] == 1 and rec["degraded"] is True

    def test_recommendation_is_json_safe(self, monkeypatch):
        ctl = policy.Controller()
        rec = ctl.observe(self._inputs(5), now=0.0)
        json.dumps(rec)  # must not raise
        for key in ("desired", "raw", "decision", "workSeconds",
                    "headroomS", "cooldownS", "hysteresis"):
            assert key in rec


# ---------------------------------------------------------------------------
# Churn geometry
# ---------------------------------------------------------------------------


def _ladder_like_tokens(count=40, seed=0):
    rng = np.random.default_rng(seed)
    toks = []
    for i in range(count):
        n = int(rng.integers(8, 200))
        v = int(rng.integers(1, 8))
        toks.append(f"vrp:{n}x{n}x{v}:tw0:het0:td{i}")
    return toks


class TestChurnGeometry:
    def test_single_member_loss_moves_exactly_its_share(self):
        for seed, n in [(0, 3), (1, 5), (2, 8)]:
            members = [f"r{seed}-{i}" for i in range(n)]
            before = HashRing(members)
            after = HashRing(members[1:])
            moved = policy.moved_fraction(before, after)
            # consistent hashing: EXACTLY the lost member's arcs move
            assert moved == pytest.approx(before.share(members[0]))
            assert 0 < moved < 2.5 / n, (n, moved)

    def test_member_join_moves_about_one_over_n(self):
        members = [f"m{i}" for i in range(4)]
        before = HashRing(members)
        after = HashRing(members + ["joiner"])
        moved = policy.moved_fraction(before, after)
        assert moved == pytest.approx(after.share("joiner"))
        assert 0 < moved < 0.5

    def test_arc_walk_agrees_with_random_probes(self):
        before = HashRing(["a", "b", "c"], vnodes=32)
        after = HashRing(["a", "b"], vnodes=32)
        exact = policy.moved_fraction(before, after)
        rng = np.random.default_rng(3)
        probes = 4000
        sampled = sum(
            1
            for s in rng.integers(0, SLOTS, size=probes)
            if before.owner(int(s)) != after.owner(int(s))
        ) / probes
        assert abs(exact - sampled) < 0.05

    def test_identical_rings_move_nothing(self):
        ring = HashRing(["a", "b", "c"])
        assert policy.moved_fraction(ring, HashRing(["c", "b", "a"])) == 0.0

    def test_inherited_tokens_match_bruteforce(self):
        toks = _ladder_like_tokens()
        for seed, n in [(0, 3), (1, 5)]:
            members = [f"w{seed}-{i}" for i in range(n)]
            before = HashRing(members)
            after = HashRing(members[1:])
            union = []
            for m in after.members:
                got = policy.inherited_tokens(before, after, m, toks)
                brute = [
                    t for t in toks
                    if after.owner(slot(t)) == m
                    and before.owner(slot(t)) != m
                ]
                assert got == brute, (m, got, brute)
                union.extend(got)
            # the lost member's tokens re-home onto survivors, exactly
            lost = [t for t in toks if before.owner(slot(t)) == members[0]]
            assert sorted(union) == sorted(lost)

    def test_new_member_inherits_everything_it_owns(self):
        toks = _ladder_like_tokens(count=20, seed=9)
        ring = HashRing(["a", "b"])
        got = policy.inherited_tokens(None, ring, "a", toks)
        assert got == [t for t in toks if ring.owner(slot(t)) == "a"]


# ---------------------------------------------------------------------------
# Scale-in victim selection
# ---------------------------------------------------------------------------

TOK16 = "vrp:16x16x4:tw0:het0:td0"
TOK32 = "vrp:32x32x4:tw1:het1:td1"


class TestVictimSelection:
    def test_mix_tier_parses_ring_tokens(self):
        assert policy.mix_tier(TOK16) == "16x4"
        assert policy.mix_tier(TOK32) == "32x4"
        assert policy.mix_tier("junk") is None
        assert policy.mix_tier("vrp:notashape:tw0") is None
        assert policy.mix_tier(None) is None

    def test_drains_replica_survivors_cover(self):
        docs = {
            "a": {"claimMix": {TOK16: 1.0}, "tiersWarmed": ["16x4"],
                  "inflight": 1},
            "b": {"claimMix": {TOK32: 1.0}, "tiersWarmed": ["16x4"],
                  "inflight": 0},
            "c": {"claimMix": {TOK32: 0.5}, "tiersWarmed": [],
                  "inflight": 0},
        }
        victim, scores = policy.choose_victim(docs)
        # only a's hot tier (16x4) is warm on its survivors
        assert victim == "a"
        assert scores["a"]["coverage"] == 1.0
        assert scores["b"]["coverage"] == 0.0

    def test_idle_replica_is_a_free_win(self):
        docs = {
            "a": {"claimMix": {TOK16: 1.0}, "tiersWarmed": [],
                  "inflight": 2},
            "b": {"claimMix": {}, "tiersWarmed": [], "inflight": 0},
        }
        victim, scores = policy.choose_victim(docs)
        assert victim == "b" and scores["b"]["coverage"] == 1.0

    def test_ties_break_on_inflight_then_id(self):
        idle = {"claimMix": {}, "tiersWarmed": []}
        victim, _ = policy.choose_victim({
            "a": dict(idle, inflight=3),
            "b": dict(idle, inflight=0),
            "c": dict(idle, inflight=1),
        })
        assert victim == "b"
        victim, _ = policy.choose_victim({
            "z": dict(idle, inflight=0),
            "a": dict(idle, inflight=0),
        })
        assert victim == "a"  # deterministic everywhere

    def test_never_drains_the_last_replica(self):
        assert policy.choose_victim({}) == (None, {})
        assert policy.choose_victim({"only": {"inflight": 0}}) == (None, {})

    def test_draining_replicas_are_not_candidates(self):
        idle = {"claimMix": {}, "tiersWarmed": [], "inflight": 0}
        victim, scores = policy.choose_victim({
            "a": dict(idle, draining=True),
            "b": dict(idle),
            "c": dict(idle, inflight=5),
        })
        assert victim == "b" and "a" not in scores
        # both remaining draining -> nobody to drain
        victim, _ = policy.choose_victim({
            "a": dict(idle, draining=True),
            "b": dict(idle, draining=True),
            "c": dict(idle),
        })
        assert victim is None


# ---------------------------------------------------------------------------
# Heartbeat-registry hygiene
# ---------------------------------------------------------------------------


class TestStaleSplit:
    def test_partitions_on_lease_window(self, monkeypatch):
        monkeypatch.setenv("VRPMS_LEASE_S", "10")
        now = 1000.0
        infos = {
            "fresh": {"updatedAt": 995.0},
            "old": {"updatedAt": 980.0},
            "undated": {"inflight": 1},
        }
        live, stale = autoscale_mod.split_stale(
            ["fresh", "old", "undated", "nodoc"], infos, now=now
        )
        assert stale == ["old"]
        # absence of evidence must not shrink the fleet
        assert live == ["fresh", "undated", "nodoc"]

    def test_zero_window_disables_staleness(self, monkeypatch):
        monkeypatch.setenv("VRPMS_LEASE_S", "0")
        live, stale = autoscale_mod.split_stale(
            ["old"], {"old": {"updatedAt": 0.0}}, now=1e9
        )
        assert live == ["old"] and stale == []


# ---------------------------------------------------------------------------
# Churn-hardening warmup tick
# ---------------------------------------------------------------------------


class _StubReplica:
    def __init__(self, rid, ring):
        self.replica_id = rid
        self._ring = ring

    def ring(self):
        return self._ring


class TestChurnWarmTick:
    @pytest.fixture()
    def launched(self, monkeypatch):
        # churn pre-warm rides the VRPMS_WARMUP switch (deployments
        # that don't warm at boot inherit nothing warm); setting it is
        # inert here — only the service CLI acts on it at startup
        monkeypatch.setenv("VRPMS_WARMUP", "tiers")
        calls = []
        monkeypatch.setattr(
            autoscale_mod, "_launch_warmup", calls.append
        )
        return calls

    def test_membership_change_warms_exactly_inherited(
        self, launched, monkeypatch
    ):
        rid = "survivor"
        # pick a peer whose loss hands rid at least one ladder tier
        # (deterministic scan — names only shift arc placement)
        pairs = autoscale_mod.ladder_tokens()
        assert pairs, "tier ladder must be on by default"
        for i in range(20):
            peer = f"peer-{i}"
            prev = HashRing([rid, peer])
            new = HashRing([rid])
            expected = autoscale_mod.inherited_spec(prev, new, rid)
            if expected:
                break
        assert expected, "no peer produced an inheritance in 20 tries"
        # brute-force the same spec straight off the ring diff
        manual = ",".join(
            shape for shape, tok in pairs
            if new.owner(slot(tok)) == rid and prev.owner(slot(tok)) != rid
        )
        assert expected == manual
        monkeypatch.setattr(
            jobs_mod, "_replica", _StubReplica(rid, new)
        )
        autoscale_mod._prev_ring = prev
        autoscale_mod._watch_churn()
        assert launched == [expected]

    def test_first_sight_and_no_change_launch_nothing(
        self, launched, monkeypatch
    ):
        ring = HashRing(["a", "b"])
        monkeypatch.setattr(
            jobs_mod, "_replica", _StubReplica("a", ring)
        )
        autoscale_mod._watch_churn()  # first observation: boot warmup
        assert launched == []
        autoscale_mod._watch_churn()  # same membership: nothing moved
        assert launched == []

    def test_no_replica_is_a_noop(self, launched, monkeypatch):
        monkeypatch.setattr(jobs_mod, "_replica", None)
        autoscale_mod._watch_churn()
        assert launched == []

    def test_no_boot_warmup_means_no_churn_warmup(
        self, launched, monkeypatch
    ):
        # a deployment that never warmed tiers has nothing warm to
        # inherit: the watcher must not start compiling on churn (test
        # fleets churn membership constantly — this is the guard that
        # keeps them compile-free)
        monkeypatch.delenv("VRPMS_WARMUP", raising=False)
        rid = "survivor"
        monkeypatch.setattr(
            jobs_mod, "_replica", _StubReplica(rid, HashRing([rid]))
        )
        autoscale_mod._prev_ring = HashRing([rid, "peer-0"])
        autoscale_mod._watch_churn()
        assert launched == []


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def server():
    import os

    os.environ["VRPMS_STORE"] = "memory"
    from service.app import serve

    jobs_mod.shutdown_scheduler()
    srv = serve(port=0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    jobs_mod.shutdown_scheduler()


def _decode(raw):
    try:
        return json.loads(raw)
    except (ValueError, TypeError):
        return {"raw": raw.decode("utf-8", "replace")}


def _post(base, path, body):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, _decode(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, _decode(e.read())


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=60) as resp:
            return resp.status, _decode(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, _decode(e.read())


class TestFleetHTTP:
    @pytest.fixture(autouse=True)
    def dist_env(self, server, monkeypatch):
        jobs_mod.shutdown_scheduler()
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_LEASE_S", "5")
        monkeypatch.setenv("VRPMS_QUEUE_POLL_MS", "10")
        # read through: tests mutate the registry and must see it
        monkeypatch.setenv("VRPMS_DEPTH_MEMO_MS", "0")
        yield
        jobs_mod.shutdown_scheduler()

    def test_fleet_publishes_autoscale_block(self, server):
        status, resp = _get(server, "/api/debug/fleet")
        assert status == 200, resp
        block = resp["fleet"]["autoscale"]
        assert block["desired"] >= 1
        assert block["decision"] in ("init", "up", "down", "hold",
                                     "cooldown", "frozen")
        assert block["degraded"] is False
        assert resp["fleet"]["members"]["live"] >= 1

    def test_stale_heartbeat_marked_and_excluded(self, server):
        qs = store.get_queue_store()
        qs.register_replica(
            "ghost-old", 60, {"updatedAt": time.time() - 999}
        )
        qs.register_replica(
            "ghost-fresh", 60, {"updatedAt": time.time()}
        )
        status, resp = _get(server, "/api/debug/fleet")
        assert status == 200, resp
        replicas = resp["fleet"]["replicas"]
        assert replicas["ghost-old"]["stale"] is True
        assert "stale" not in replicas["ghost-fresh"]
        # live = fresh ghost + this process; the crashed doc is OUT
        assert resp["fleet"]["members"] == {"live": 2, "stale": 1}

    def test_faulty_store_freezes_degraded_never_500s(
        self, server, monkeypatch
    ):
        # prime a non-trivial recommendation while the store works
        monkeypatch.setenv("VRPMS_AUTOSCALE_HEADROOM_S", "10")
        ctl = autoscale_mod.controller()
        ctl.observe(
            {"depth": 30, "jobSeconds": 1.0, "perReplica": 1,
             "members": 1},
            now=0.0,
        )
        assert ctl.desired() == 3
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        for _ in range(3):
            status, resp = _get(server, "/api/debug/fleet")
            assert status == 200, resp  # the chaos contract: never 500
            block = resp["fleet"]["autoscale"]
            assert block["decision"] == "frozen"
            assert block["degraded"] is True
            assert block["desired"] == 3  # frozen, not re-guessed
        # the preview surface survives the outage too
        status, resp = _get(server, "/api/admin/scalein")
        assert status == 200, resp
        # store back: the controller recovers without a restart
        monkeypatch.setenv("VRPMS_STORE", "memory")
        status, resp = _get(server, "/api/debug/fleet")
        assert status == 200, resp
        assert resp["fleet"]["autoscale"]["degraded"] is False

    def test_scalein_refuses_last_replica(self, server):
        status, resp = _post(server, "/api/admin/scalein", {})
        assert status == 409, resp
        assert not resp["success"]
        status, resp = _get(server, "/api/admin/scalein")
        assert status == 200 and resp["scalein"]["victim"] is None

    def test_scalein_unknown_replica_404s(self, server):
        status, resp = _post(
            server, "/api/admin/scalein", {"replicaId": "nope"}
        )
        assert status == 404, resp

    def test_scalein_unreachable_victim_502s(self, server):
        qs = store.get_queue_store()
        qs.register_replica(
            "ghost-no-addr", 60, {"updatedAt": time.time()}
        )
        status, resp = _post(
            server, "/api/admin/scalein", {"replicaId": "ghost-no-addr"}
        )
        assert status == 502, resp
        qs.register_replica(
            "ghost-dead-addr", 60,
            {"updatedAt": time.time(), "addr": "127.0.0.1:9"},
        )
        status, resp = _post(
            server, "/api/admin/scalein", {"replicaId": "ghost-dead-addr"}
        )
        assert status == 502, resp
        # nothing was half-drained on this replica
        status, resp = _get(server, "/api/admin/drain")
        assert status == 200
        assert not (resp.get("drain") or {}).get("draining")

    def test_scalein_self_victim_drains_locally(self, server):
        qs = store.get_queue_store()
        # a hot peer makes this (idle) process the natural victim
        qs.register_replica(
            "busy-peer", 60,
            {"updatedAt": time.time(), "inflight": 5,
             "claimMix": {TOK16: 1.0}, "tiersWarmed": []},
        )
        status, resp = _post(server, "/api/admin/scalein", {"graceS": 0})
        assert status == 202, resp
        scalein = resp["scalein"]
        assert scalein["local"] is True
        assert scalein["victim"] == jobs_mod.replica_id()
        assert scalein["drain"]["draining"] is True
        # the audit trail survives on the GET surface
        status, resp = _get(server, "/api/admin/scalein")
        assert status == 200
        assert resp["last"]["victim"] == jobs_mod.replica_id()

    def test_drain_second_post_reports_already_draining(self, server):
        drains_before = sum(
            1 for t in threading.enumerate() if t.name == "vrpms-drain"
        )
        status, first = _post(server, "/api/admin/drain", {})
        assert status == 202, first
        assert "alreadyDraining" not in first["drain"]
        status, second = _post(server, "/api/admin/drain", {})
        assert status == 202, second
        assert second["drain"]["alreadyDraining"] is True
        # the marker lives only in the POST return, never in the state
        status, state = _get(server, "/api/admin/drain")
        assert status == 200
        assert "alreadyDraining" not in (state.get("drain") or {})
        # idempotent truly: the second POST spawned no second worker
        drains_after = sum(
            1 for t in threading.enumerate() if t.name == "vrpms-drain"
        )
        assert drains_after <= drains_before + 1


# ---------------------------------------------------------------------------
# VRPMS_AUTOSCALE=off — byte identity
# ---------------------------------------------------------------------------


def _seed_dataset(key="as7", n=7, seed=11):
    rng = np.random.default_rng(seed)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        key, [{"id": i, "demand": 2 if i else 0} for i in range(n)]
    )
    mem.seed_durations(key, d.tolist())


def _solve_body(key="as7", n=7):
    return {
        "solutionName": f"as-{key}",
        "solutionDescription": "t",
        "locationsKey": key,
        "durationsKey": key,
        "capacities": [2 * n] * 3,
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": 7,
        "iterationCount": 200,
        "populationSize": 8,
    }


class TestAutoscaleOff:
    @pytest.fixture(autouse=True)
    def local_env(self, server, monkeypatch):
        jobs_mod.shutdown_scheduler()
        # cache off: the second identical request must SOLVE again or
        # cacheHit would (legitimately) differ between the responses
        monkeypatch.setenv("VRPMS_CACHE", "off")
        _seed_dataset()
        yield
        jobs_mod.shutdown_scheduler()

    def test_fleet_has_no_autoscale_keys_when_off(
        self, server, monkeypatch
    ):
        monkeypatch.setenv("VRPMS_AUTOSCALE", "off")
        status, resp = _get(server, "/api/debug/fleet")
        assert status == 200, resp
        assert "autoscale" not in resp["fleet"]
        assert "members" not in resp["fleet"]

    def test_scalein_route_404s_when_off(self, server, monkeypatch):
        status, _ = _get(server, "/api/admin/scalein")
        assert status == 200  # on by default
        monkeypatch.setenv("VRPMS_AUTOSCALE", "off")
        status, _ = _get(server, "/api/admin/scalein")
        assert status == 404
        status, _ = _post(server, "/api/admin/scalein", {})
        assert status == 404

    def test_fixed_seed_solves_byte_identical_on_off(
        self, server, monkeypatch
    ):
        status, on_resp = _post(server, "/api/vrp/sa", _solve_body())
        assert status == 200, on_resp
        monkeypatch.setenv("VRPMS_AUTOSCALE", "off")
        status, off_resp = _post(server, "/api/vrp/sa", _solve_body())
        assert status == 200, off_resp
        assert on_resp["message"] == off_resp["message"]
