"""Unit tests for the span-tracing subsystem (vrpms_tpu.obs.spans).

Model behavior (span tree, attributes, events, caps), W3C traceparent
parsing with its full malformed-header ladder (a bad header means a
fresh trace, never an error), context propagation across threads (the
scheduler hop the Job models), the completed-trace ring with its
filters, slow-trace auto-capture, and the registry's histogram
exemplars.
"""

import io
import json
import threading

import pytest

from vrpms_tpu.obs import Registry, collect_blocks, set_log_stream, spans


@pytest.fixture(autouse=True)
def clean_ring():
    spans.reset_ring()
    yield
    spans.reset_ring()


class TestSpanModel:
    def test_root_and_children(self):
        t = spans.Trace()
        tokens = spans.activate(t)
        try:
            with spans.span("root") as root:
                with spans.span("child", algorithm="sa") as child:
                    child.event("tick", n=1)
                with spans.span("sibling"):
                    pass
        finally:
            spans.deactivate(tokens)
        wf = t.waterfall()
        assert [s["name"] for s in wf] == ["root", "child", "sibling"]
        by_name = {s["name"]: s for s in wf}
        assert by_name["child"]["parentId"] == by_name["root"]["spanId"]
        assert by_name["sibling"]["parentId"] == by_name["root"]["spanId"]
        assert by_name["root"]["parentId"] is None
        assert by_name["child"]["attributes"]["algorithm"] == "sa"
        assert by_name["child"]["events"][0]["name"] == "tick"
        for s in wf:
            assert s["durationMs"] is not None and s["durationMs"] >= 0
            assert len(s["spanId"]) == 16

    def test_span_without_trace_is_noop(self):
        assert spans.current_trace() is None
        with spans.span("nothing") as s:
            assert s is None
        assert spans.current_span() is None

    def test_exception_marks_error_and_reraises(self):
        t = spans.Trace()
        tokens = spans.activate(t)
        try:
            with pytest.raises(ValueError):
                with spans.span("boom"):
                    raise ValueError("nope")
        finally:
            spans.deactivate(tokens)
        (s,) = t.waterfall()
        assert s["status"] == "error"
        assert "ValueError" in s["attributes"]["error"]

    def test_end_is_idempotent_first_wins(self):
        t = spans.Trace()
        s = t.span("once")
        s.end()
        first = s.duration_ms
        s.end(status="error")
        assert s.duration_ms == first
        assert s.status == "error"  # status may still be corrected

    def test_span_cap_truncates_but_returns_usable_span(self):
        t = spans.Trace()
        for i in range(spans.MAX_SPANS_PER_TRACE + 5):
            s = t.span(f"s{i}")
            s.end()
        assert len(t.spans) == spans.MAX_SPANS_PER_TRACE
        assert t.truncated

    def test_event_cap(self):
        t = spans.Trace()
        s = t.span("busy")
        for i in range(spans.MAX_EVENTS_PER_SPAN + 10):
            s.event("e", i=i)
        assert len(s.events) == spans.MAX_EVENTS_PER_SPAN
        assert t.truncated

    def test_retroactive_span_at(self):
        import time

        t = spans.Trace()
        now = time.monotonic()
        s = t.span_at("queue.wait", None, now - 0.25, 0.25, jobId="j1")
        assert s.duration_ms == 250.0
        assert s.attributes["jobId"] == "j1"

    def test_cross_thread_activation(self):
        """The scheduler hop: a worker thread re-activates the carried
        context and its spans land in the same trace."""
        t = spans.Trace()
        root = t.span("root")
        seen = {}

        def worker():
            tokens = spans.activate(t, root)
            try:
                with spans.span("solve") as s:
                    seen["trace_id"] = spans.current_trace_id()
                    seen["parent"] = s.parent_id
            finally:
                spans.deactivate(tokens)

        th = threading.Thread(target=worker)
        th.start()
        th.join()
        assert seen["trace_id"] == t.trace_id
        assert seen["parent"] == root.span_id
        assert [s.name for s in t.spans] == ["root", "solve"]

    def test_waterfall_is_json_serializable(self):
        t = spans.Trace()
        with_tokens = spans.activate(t)
        with spans.span("a", n=3, label="x"):
            spans.add_event("ev", v=1.5)
        spans.deactivate(with_tokens)
        json.dumps(t.waterfall())


class TestTraceparent:
    GOOD = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"

    def test_valid_header_adopted(self):
        tid, pid = spans.parse_traceparent(self.GOOD)
        assert tid == "ab" * 16
        assert pid == "cd" * 8
        t = spans.start_trace(self.GOOD)
        assert t.trace_id == tid and t.remote_parent_id == pid
        root = t.span("root")
        assert root.parent_id == pid  # parents under the remote span

    @pytest.mark.parametrize(
        "header",
        [
            None,
            "",
            "garbage",
            "00-short-" + "cd" * 8 + "-01",                     # bad trace len
            "00-" + "ab" * 16 + "-short-01",                    # bad span len
            "0-" + "ab" * 16 + "-" + "cd" * 8 + "-01",          # bad version len
            "zz-" + "ab" * 16 + "-" + "cd" * 8 + "-01",         # non-hex version
            "ff-" + "ab" * 16 + "-" + "cd" * 8 + "-01",         # forbidden ff
            "00-" + "AB" * 16 + "-" + "cd" * 8 + "-01",         # uppercase hex
            "00-" + "0" * 32 + "-" + "cd" * 8 + "-01",          # all-zero trace
            "00-" + "ab" * 16 + "-" + "0" * 16 + "-01",         # all-zero span
            "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01-extra",   # v00 extra part
            "00-" + "ab" * 16 + "-" + "cd" * 8 + "-0x",         # non-hex flags
            "00-" + "ab" * 16 + "-" + "cd" * 8,                 # missing flags
            "00-" + "ab" * 5000 + "-" + "cd" * 8 + "-01",       # oversized
        ],
    )
    def test_malformed_header_means_fresh_trace(self, header):
        tid, pid = spans.parse_traceparent(header)
        assert tid is None and pid is None
        t = spans.start_trace(header)
        assert t is not None
        assert len(t.trace_id) == 32 and t.remote_parent_id is None

    def test_future_version_tolerated(self):
        # W3C: unknown versions parse the known prefix (extra parts ok)
        header = "01-" + "ab" * 16 + "-" + "cd" * 8 + "-01-whatever"
        tid, pid = spans.parse_traceparent(header)
        assert tid == "ab" * 16 and pid == "cd" * 8

    def test_format_roundtrip(self):
        tid, sid = spans.new_trace_id(), spans.new_span_id()
        out = spans.format_traceparent(tid, sid)
        assert spans.parse_traceparent(out) == (tid, sid)

    def test_tracing_off_disables(self, monkeypatch):
        monkeypatch.setenv("VRPMS_TRACING", "off")
        assert spans.start_trace(self.GOOD) is None


class TestRing:
    def _finished_trace(self, name="root", status=None):
        t = spans.Trace()
        t.span(name).end()
        t.finish(status=status)
        return t

    def test_finish_pushes_once(self):
        t = self._finished_trace()
        t.finish()  # idempotent
        assert spans.ring_size() == 1
        assert spans.ring_get(t.trace_id) is t

    def test_empty_trace_not_retained(self):
        t = spans.Trace()
        t.finish()
        assert spans.ring_size() == 0

    def test_capacity_evicts_oldest(self):
        spans.reset_ring(capacity=3)
        traces = [self._finished_trace() for _ in range(5)]
        assert spans.ring_size() == 3
        assert spans.ring_get(traces[0].trace_id) is None
        assert spans.ring_get(traces[-1].trace_id) is traces[-1]

    def test_snapshot_filters(self):
        slow = spans.Trace()
        s = slow.span_at("root", None, slow.start_mono, 2.0)  # 2000 ms
        s.end()
        slow.finish()
        fast = self._finished_trace()
        bad = self._finished_trace(status="error")
        got = spans.ring_snapshot(min_duration_ms=1000.0)
        assert [g["traceId"] for g in got] == [slow.trace_id]
        got = spans.ring_snapshot(status="error")
        assert [g["traceId"] for g in got] == [bad.trace_id]
        assert len(spans.ring_snapshot(limit=2)) == 2
        # newest first
        all_ids = [g["traceId"] for g in spans.ring_snapshot()]
        assert all_ids == [bad.trace_id, fast.trace_id, slow.trace_id]

    def test_env_ring_capacity(self, monkeypatch):
        monkeypatch.setenv("VRPMS_TRACE_RING", "2")
        spans.reset_ring()
        assert spans.ring_capacity() == 2


class TestSlowCapture:
    def test_slow_trace_logged_with_waterfall(self, monkeypatch):
        monkeypatch.setenv("VRPMS_TRACE_SLOW_MS", "1")
        t = spans.Trace()
        t.span_at("solve", None, t.start_mono, 0.05).end()
        buf = io.StringIO()
        prev = set_log_stream(buf)
        try:
            t.finish()
        finally:
            set_log_stream(prev)
        (line,) = [
            ln for ln in buf.getvalue().splitlines() if "trace.slow" in ln
        ]
        rec = json.loads(line)
        assert rec["traceId"] == t.trace_id
        assert rec["durationMs"] >= 1
        assert [s["name"] for s in rec["spans"]] == ["solve"]

    def test_fast_trace_not_logged(self, monkeypatch):
        monkeypatch.setenv("VRPMS_TRACE_SLOW_MS", "60000")
        t = spans.Trace()
        t.span("quick").end()
        buf = io.StringIO()
        prev = set_log_stream(buf)
        try:
            t.finish()
        finally:
            set_log_stream(prev)
        assert "trace.slow" not in buf.getvalue()


class TestBlockTraceFeedsSpans:
    def test_block_entries_become_span_events(self):
        t = spans.Trace()
        tokens = spans.activate(t)
        try:
            with spans.span("solver.solve") as s:
                with collect_blocks() as bt:
                    bt.record([5.0, 3.0], iters=128, evals_per_iter=4)
                    bt.record([2.5], iters=128, evals_per_iter=4)
        finally:
            spans.deactivate(tokens)
        events = [e for e in s.events if e["name"] == "block"]
        assert [e["evals"] for e in events] == [512, 1024]
        assert [e["bestCost"] for e in events] == [3.0, 2.5]


class TestHistogramExemplars:
    def test_worst_per_bucket_remembered(self):
        reg = Registry()
        h = reg.histogram("lat", "h", buckets=(1, 10))
        h.observe(0.5, trace_id="t-small")
        h.observe(0.9, trace_id="t-big")
        h.observe(0.7, trace_id="t-mid")
        h.observe(5.0, trace_id="t-other-bucket")
        out = reg.render(openmetrics=True)
        assert 'lat_bucket{le="1"} 3 # {trace_id="t-big"} 0.9' in out
        assert 'lat_bucket{le="10"} 4 # {trace_id="t-other-bucket"} 5' in out
        assert out.endswith("# EOF\n")

    def test_classic_render_is_exemplar_free_and_preserves_them(self):
        # exemplars are OpenMetrics-only: one in the classic 0.0.4
        # output would fail the WHOLE scrape of a classic parser — and
        # a classic scrape must not drain the window's exemplars either
        reg = Registry()
        h = reg.histogram("lat", "h", buckets=(1,))
        h.observe(0.5, trace_id="t1")
        classic = reg.render()
        assert "trace_id" not in classic and "# EOF" not in classic
        assert 'trace_id="t1"' in reg.render(openmetrics=True)

    def test_openmetrics_render_drains_exemplars(self):
        reg = Registry()
        h = reg.histogram("lat", "h", buckets=(1,))
        h.observe(0.5, trace_id="t1")
        first = reg.render(openmetrics=True)
        assert 'trace_id="t1"' in first
        second = reg.render(openmetrics=True)
        assert "trace_id" not in second  # since-last-scrape semantics
        assert 'lat_bucket{le="1"} 1' in second  # counts persist

    def test_openmetrics_family_naming(self):
        reg = Registry()
        reg.counter("req_total", "h").inc()
        om = reg.render(openmetrics=True)
        # the counter FAMILY drops _total; the sample keeps it
        assert "# TYPE req counter" in om
        assert "req_total 1" in om
        classic = reg.render()
        assert "# TYPE req_total counter" in classic

    def test_no_trace_id_no_exemplar(self):
        reg = Registry()
        h = reg.histogram("lat", "h", buckets=(1,))
        h.observe(0.5)
        assert "trace_id" not in reg.render(openmetrics=True)

    def test_labelled_children_carry_exemplars(self):
        reg = Registry()
        h = reg.histogram("lat", "h", labels=("algo",), buckets=(1,))
        h.labels(algo="sa").observe(0.5, trace_id="abc")
        out = reg.render(openmetrics=True)
        assert 'lat_bucket{algo="sa",le="1"} 1 # {trace_id="abc"} 0.5' in out

    def test_disabled_registry_records_nothing(self):
        reg = Registry(enabled=False)
        h = reg.histogram("lat", "h", buckets=(1,))
        h.observe(0.5, trace_id="t1")
        assert "trace_id" not in reg.render(openmetrics=True)
