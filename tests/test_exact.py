"""Held-Karp exact TSP vs the brute-force oracle."""

import numpy as np
import pytest

from vrpms_tpu.core import make_instance
from vrpms_tpu.core.encoding import is_valid_giant
from vrpms_tpu.solvers import solve_tsp_bf, solve_tsp_exact
from vrpms_tpu.solvers.exact import MAX_EXACT_CUSTOMERS
from tests.test_core_cost import random_instance


class TestHeldKarp:
    @pytest.mark.parametrize("n", [3, 5, 8])
    def test_matches_bf(self, rng, n):
        d = rng.uniform(1, 50, size=(n + 1, n + 1))
        np.fill_diagonal(d, 0)
        inst = make_instance(d, n_vehicles=1)
        want = float(solve_tsp_bf(inst).cost)
        res = solve_tsp_exact(inst)
        assert np.isclose(float(res.cost), want, rtol=1e-5)
        assert is_valid_giant(res.giant, n, 1)

    def test_asymmetric_matches_bf(self, rng):
        n = 6
        d = rng.uniform(1, 50, size=(n + 1, n + 1))  # asymmetric on purpose
        np.fill_diagonal(d, 0)
        inst = make_instance(d, n_vehicles=1)
        assert np.isclose(
            float(solve_tsp_exact(inst).cost), float(solve_tsp_bf(inst).cost), rtol=1e-5
        )

    def test_beyond_bf_bound(self, rng):
        # 12 customers: infeasible for itertools-scale checks, fine for HK.
        n = 12
        d = rng.uniform(1, 50, size=(n + 1, n + 1))
        d = (d + d.T) / 2
        np.fill_diagonal(d, 0)
        inst = make_instance(d, n_vehicles=1)
        res = solve_tsp_exact(inst)
        assert is_valid_giant(res.giant, n, 1)
        # sanity: exact must be no worse than nearest-neighbor + 2-opt
        from vrpms_tpu.solvers import solve_nn_2opt

        assert float(res.cost) <= float(solve_nn_2opt(inst).cost) + 1e-3

    def test_rejects_large_and_timed(self, rng):
        # random_instance's n is the node count; customers = n - 1
        inst = random_instance(rng, n=MAX_EXACT_CUSTOMERS + 2, v=1)
        with pytest.raises(ValueError, match="exceeds"):
            solve_tsp_exact(inst)
        timed = random_instance(rng, n=5, v=1, tw=True)
        with pytest.raises(ValueError, match="time"):
            solve_tsp_exact(timed)


class TestBranchAndBound:
    """solve_cvrp_bnb vs the BF oracle, plus the fixture optimality
    proofs that pin the embedded public instances (VERDICT r2 item 3)."""

    def test_matches_bf_random(self, rng):
        from vrpms_tpu.solvers import solve_vrp_bf
        from vrpms_tpu.solvers.exact import solve_cvrp_bnb

        for _ in range(4):
            n = int(rng.integers(5, 9))
            V = int(rng.integers(2, 4))
            pts = rng.uniform(0, 100, (n + 1, 2))
            d = np.linalg.norm(pts[:, None] - pts[None], axis=-1)
            dem = np.concatenate([[0], rng.integers(1, 10, n)])
            cap = float(max(dem.max(), int(dem.sum() / V * 1.4)))
            inst = make_instance(d, demands=dem, capacities=[cap] * V)
            res, proven, _ = solve_cvrp_bnb(inst)
            assert proven
            assert np.isclose(float(res.cost), float(solve_vrp_bf(inst).cost), rtol=1e-5)

    def test_native_matches_python(self, rng):
        # the C++ DFS and the Python twin walk the same tree definition;
        # both must land on the identical proven optimum (the Python
        # engine is the oracle the native one is checked against)
        from vrpms_tpu.solvers.exact import solve_cvrp_bnb

        for _ in range(3):
            n = int(rng.integers(8, 13))
            V = int(rng.integers(2, 4))
            pts = rng.uniform(0, 100, (n + 1, 2))
            d = np.linalg.norm(pts[:, None] - pts[None], axis=-1)
            dem = np.concatenate([[0], rng.integers(1, 10, n)])
            cap = float(max(dem.max(), int(dem.sum() / V * 1.4)))
            inst = make_instance(d, demands=dem, capacities=[cap] * V)
            res_n, proven_n, stats_n = solve_cvrp_bnb(inst)
            res_p, proven_p, stats_p = solve_cvrp_bnb(inst, use_native=False)
            assert proven_p and stats_p["engine"] == "python"
            assert np.isclose(float(res_n.cost), float(res_p.cost), rtol=1e-6)
            if stats_n["engine"] == "native":  # toolchain present
                assert proven_n

    def test_parallel_engine_matches_sequential(self, rng):
        # the depth-2 task-queue engine (round 4) must prove the same
        # optimum as the sequential walk at every thread count — on this
        # 1-core host the speedup is structural, not wall-clock, but the
        # equivalence is what guards the shared-incumbent/task algebra
        from vrpms_tpu.solvers.exact import solve_cvrp_bnb

        n, V = 12, 3
        pts = rng.uniform(0, 100, (n + 1, 2))
        d = np.linalg.norm(pts[:, None] - pts[None], axis=-1)
        dem = np.concatenate([[0], rng.integers(1, 10, n)])
        cap = float(max(dem.max(), int(dem.sum() / V * 1.4)))
        inst = make_instance(d, demands=dem, capacities=[cap] * V)
        costs = []
        for nt in (1, 2, 4):
            res, proven, stats = solve_cvrp_bnb(inst, n_threads=nt)
            if stats["engine"] != "native":
                pytest.skip("no native toolchain")
            assert proven
            costs.append(float(res.cost))
        assert np.allclose(costs, costs[0], rtol=1e-9)

    def test_cost_only_incumbent_never_claims_proven_fallback(self):
        # an incumbent COST below anything reachable must not stamp the
        # NN fallback as a proven optimum (code-review round 3 finding)
        from vrpms_tpu.io.fixtures import load_fixture
        from vrpms_tpu.solvers.exact import solve_cvrp_bnb

        inst, _ = load_fixture("E-n22-k4")
        # 300 < optimum 375: the tree exhausts finding nothing
        res, proven, stats = solve_cvrp_bnb(
            inst, time_limit_s=60, incumbent_cost=300.0
        )
        assert not proven
        assert float(res.breakdown.cap_excess) == 0.0  # NN fallback returned

    def test_non_integer_demands_use_ap_path(self, rng):
        # fractional demands disable the q-route tables; the AP-bound
        # fallback must still prove small instances
        from vrpms_tpu.solvers import solve_vrp_bf
        from vrpms_tpu.solvers.exact import solve_cvrp_bnb

        n, V = 6, 2
        pts = rng.uniform(0, 100, (n + 1, 2))
        d = np.linalg.norm(pts[:, None] - pts[None], axis=-1)
        dem = np.concatenate([[0], rng.uniform(1, 8, n)])
        cap = float(dem.sum() / V * 1.4)
        inst = make_instance(d, demands=dem, capacities=[cap] * V)
        res, proven, stats = solve_cvrp_bnb(inst)
        assert proven and stats["qroute_bound"] is None
        assert np.isclose(float(res.cost), float(solve_vrp_bf(inst).cost), rtol=1e-5)

    def test_time_limit_returns_incumbent(self):
        from vrpms_tpu.io.fixtures import load_fixture
        from vrpms_tpu.solvers.exact import solve_cvrp_bnb

        inst, _ = load_fixture("A-n32-k5")
        res, proven, _ = solve_cvrp_bnb(inst, time_limit_s=0.2, incumbent_cost=900.0)
        # 0.2 s cannot exhaust n=32: must come back unproven with a
        # capacity-feasible best-effort solution (the NN fallback; the
        # caller's 900 was a bound, not routes, so it cannot be returned)
        assert not proven
        assert float(res.breakdown.cap_excess) == 0.0
        assert np.isfinite(float(res.breakdown.distance))

    def test_enum_certificate_never_proves_infeasible_fallback(self, rng):
        """ADVICE r5 het-fleet hole, pinned: a COMPLETE untimed
        enumeration whose every order had a capacity-infeasible optimal
        split (total demand > total fleet capacity) falls back to a
        penalized greedy packing — the certificate must report that as
        unproven + infeasible, never as a proven optimum."""
        from vrpms_tpu.solvers import solve_vrp_bf
        from service.solve import _enum_certificate

        pts = rng.uniform(0, 100, (6, 2))
        d = np.linalg.norm(pts[:, None] - pts[None], axis=-1)
        # het fleet, total demand 10 > total capacity 7
        inst = make_instance(
            d, demands=[0, 2, 2, 2, 2, 2], capacities=[4.0, 3.0]
        )
        res = solve_vrp_bf(inst)
        assert int(res.evals) >= 120  # 5! orders: enumeration COMPLETE
        assert float(res.breakdown.cap_excess) > 0.0  # fallback packing
        cert = _enum_certificate(res, inst, split_exact=True)
        assert cert["proven"] is False
        assert cert["infeasible"] is True
        # ... while the same fleet with enough capacity stays provable
        feasible = make_instance(
            d, demands=[0, 2, 2, 2, 2, 2], capacities=[6.0, 5.0]
        )
        res2 = solve_vrp_bf(feasible)
        cert2 = _enum_certificate(res2, feasible, split_exact=True)
        assert cert2["proven"] is True
        assert "infeasible" not in cert2

    def test_proves_e_n22_k4_optimum(self):
        # The strongest fixture cross-check there is: the branch-and-bound
        # proves the embedded E-n22-k4 transcription has optimum exactly
        # 375 — the published value. A transcription error in coords or
        # demands would move the proven optimum away from 375.
        from vrpms_tpu.io.fixtures import load_fixture
        from vrpms_tpu.solvers.exact import solve_cvrp_bnb

        inst, meta = load_fixture("E-n22-k4")
        res, proven, stats = solve_cvrp_bnb(inst, time_limit_s=120, incumbent_cost=376.0)
        assert proven
        assert float(res.breakdown.distance) == meta["bks"] == 375.0
