"""Held-Karp exact TSP vs the brute-force oracle."""

import numpy as np
import pytest

from vrpms_tpu.core import make_instance
from vrpms_tpu.core.encoding import is_valid_giant
from vrpms_tpu.solvers import solve_tsp_bf, solve_tsp_exact
from vrpms_tpu.solvers.exact import MAX_EXACT_CUSTOMERS
from tests.test_core_cost import random_instance


class TestHeldKarp:
    @pytest.mark.parametrize("n", [3, 5, 8])
    def test_matches_bf(self, rng, n):
        d = rng.uniform(1, 50, size=(n + 1, n + 1))
        np.fill_diagonal(d, 0)
        inst = make_instance(d, n_vehicles=1)
        want = float(solve_tsp_bf(inst).cost)
        res = solve_tsp_exact(inst)
        assert np.isclose(float(res.cost), want, rtol=1e-5)
        assert is_valid_giant(res.giant, n, 1)

    def test_asymmetric_matches_bf(self, rng):
        n = 6
        d = rng.uniform(1, 50, size=(n + 1, n + 1))  # asymmetric on purpose
        np.fill_diagonal(d, 0)
        inst = make_instance(d, n_vehicles=1)
        assert np.isclose(
            float(solve_tsp_exact(inst).cost), float(solve_tsp_bf(inst).cost), rtol=1e-5
        )

    def test_beyond_bf_bound(self, rng):
        # 12 customers: infeasible for itertools-scale checks, fine for HK.
        n = 12
        d = rng.uniform(1, 50, size=(n + 1, n + 1))
        d = (d + d.T) / 2
        np.fill_diagonal(d, 0)
        inst = make_instance(d, n_vehicles=1)
        res = solve_tsp_exact(inst)
        assert is_valid_giant(res.giant, n, 1)
        # sanity: exact must be no worse than nearest-neighbor + 2-opt
        from vrpms_tpu.solvers import solve_nn_2opt

        assert float(res.cost) <= float(solve_nn_2opt(inst).cost) + 1e-3

    def test_rejects_large_and_timed(self, rng):
        # random_instance's n is the node count; customers = n - 1
        inst = random_instance(rng, n=MAX_EXACT_CUSTOMERS + 2, v=1)
        with pytest.raises(ValueError, match="exceeds"):
            solve_tsp_exact(inst)
        timed = random_instance(rng, n=5, v=1, tw=True)
        with pytest.raises(ValueError, match="time"):
            solve_tsp_exact(timed)
