"""Three-way cross-checks for the embedded public benchmark fixtures.

The fixtures in vrpms_tpu/io/fixtures/ are hand-embedded transcriptions of
public benchmark data (zero-egress container), so each one is defended
against transcription error (VERDICT round-2 item 1):

  (a) self-consistency — demand totals vs capacity arithmetic, the `-kV`
      fleet matching the bin-packing minimum, symmetric nint() matrices,
      sane time windows;
  (b) lower_bound(inst) <= BKS — a violated LB proves bad data;
  (c) the solver lands INSIDE [BKS, 1.2*BKS] — strictly better than the
      published optimum proves bad data just as surely as way worse
      proves a bad solver. (ILS hits E-n22-k4=375, A-n32-k5=784 and
      C101.25=191.3 exactly; see also test_exact.py's branch-and-bound
      optimality proofs of the CVRP fixtures.)
"""

import numpy as np
import pytest

from vrpms_tpu.io import bounds
from vrpms_tpu.io.fixtures import FIXTURES, fixture_names, load_fixture
from vrpms_tpu.solvers import ILSParams, SAParams, solve_ils


class TestSelfConsistency:
    @pytest.mark.parametrize("name", fixture_names())
    def test_loads_and_shapes(self, name):
        inst, meta = load_fixture(name)
        assert meta["bks"] > 0
        d = np.asarray(inst.durations[0])
        assert d.shape == (inst.n_nodes, inst.n_nodes)
        assert np.allclose(np.diag(d), 0.0)
        assert np.allclose(d, d.T)  # EUC_2D is symmetric
        assert float(np.asarray(inst.demands)[0]) == 0.0  # depot

    @pytest.mark.parametrize(
        "name", [n for n in fixture_names() if FIXTURES[n][1] == "cvrp"]
    )
    def test_cvrp_fleet_is_binpacking_minimum(self, name):
        # the registry only admits CVRP instances whose -kV fleet equals
        # the bin-packing minimum: that is what makes the published
        # fixed-fleet optimum comparable to this framework's
        # idle-vehicles-allowed objective (see fixtures.py on P-n16-k8)
        inst, meta = load_fixture(name)
        assert inst.n_vehicles == meta["bks_vehicles"]
        assert bounds.route_count_lb(inst) == inst.n_vehicles
        caps = np.asarray(inst.capacities)
        dem = np.asarray(inst.demands)
        assert dem.sum() <= caps.sum()
        assert dem.max() <= caps.max()

    @pytest.mark.parametrize(
        "name", [n for n in fixture_names() if FIXTURES[n][1] == "vrptw"]
    )
    def test_solomon_windows_sane(self, name):
        inst, meta = load_fixture(name)
        ready = np.asarray(inst.ready)
        due = np.asarray(inst.due)
        service = np.asarray(inst.service)
        assert (ready <= due).all()
        assert (due[1:] <= due[0]).all()  # depot horizon dominates
        assert (service[1:] > 0).all() and service[0] == 0
        # every customer individually reachable within its window from a
        # depot start at time 0 (else the instance would be infeasible)
        d = np.asarray(inst.durations[0])
        assert (d[0, 1:] <= due[1:]).all()

    @pytest.mark.parametrize("name", fixture_names())
    def test_lower_bound_at_most_bks(self, name):
        inst, meta = load_fixture(name)
        # the same bound family lower_bound() maxes over, but with a
        # SHORT ascent: the production 1500-iteration certificate run
        # costs ~6 min of CPU on E-n51 alone, and every iterate is a
        # valid LB anyway — a violated bound convicts the transcription
        # at 120 iterations exactly as surely
        lb = max(
            bounds.assignment_lb(inst),
            bounds.mst_lb(inst),
            bounds.cvrp_forest_lb(inst),
            bounds.cmt_qroute_lb(inst, iters=120, ub=meta["bks"]),
        )
        assert 0 < lb <= meta["bks"] + 1e-6


class TestR101Full:
    """Targeted checks for the XL fixture (full 100-customer R101, too
    big for the per-fixture short-ILS band test on CPU): the certified
    prefix identity is the transcription anchor — rows 1-25 were proven
    exact in round 3 (the solver hit Kohl's 617.1 optimum on them)."""

    def test_prefix_exactly_matches_certified_r101_25(self):
        import re

        from vrpms_tpu.io.fixtures import fixture_path

        def rows(path, upto):
            out = {}
            for ln in open(path):
                s = ln.split()
                if s and re.fullmatch(r"\d+", s[0]) and len(s) >= 7:
                    i = int(s[0])
                    if i <= upto:
                        out[i] = tuple(float(x) for x in s[1:7])
            return out

        small = rows(fixture_path("R101.25"), 25)
        full = rows(fixture_path("R101"), 25)
        assert small == full and len(small) == 26  # depot + 25

    def test_loads_sane_and_lb_below_bks(self):
        inst, meta = load_fixture("R101")
        assert inst.n_customers == 100
        assert meta["bks"] == 1637.7
        ready = np.asarray(inst.ready)
        due = np.asarray(inst.due)
        service = np.asarray(inst.service)
        assert (ready <= due).all()
        assert (due[1:] <= due[0]).all()
        assert (service[1:] > 0).all() and service[0] == 0
        d = np.asarray(inst.durations[0])
        assert (d[0, 1:] <= due[1:]).all()  # every customer reachable
        # cheap members of the bound family only (the full lower_bound
        # runs a 1500-iteration certificate ascent — minutes of CPU in
        # a unit test); each alone is a valid LB so the check still
        # convicts a transcription whose data inflates distances
        lb = max(bounds.assignment_lb(inst), bounds.mst_lb(inst))
        assert 0 < lb <= meta["bks"] + 1e-6
        # demand arithmetic: 100 customers fit the 20-vehicle BKS fleet
        dem = np.asarray(inst.demands)
        caps = np.asarray(inst.capacities)
        assert dem.sum() <= caps.sum() and dem.max() <= caps.max()


class TestSolverBand:
    """Slow: a short ILS must land in [BKS, 1.2*BKS] on every fixture."""

    @pytest.mark.parametrize("name", fixture_names())
    def test_ils_band(self, name):
        inst, meta = load_fixture(name)
        params = ILSParams(
            rounds=2,
            sa=SAParams(n_chains=256, n_iters=2500),
            pool=16,
            polish_sweeps=64,
        )
        res = solve_ils(inst, key=0, params=params)
        cost = float(res.cost)
        bks = meta["bks"]
        assert cost >= bks - 1e-4, f"{name}: {cost} BEATS published BKS {bks} — bad data"
        assert cost <= 1.2 * bks, f"{name}: {cost} too far above BKS {bks}"
