"""One-hot (MXU) hot-path equivalence vs the gather formulation.

The one-hot path exists because TPU lowers elementwise data-dependent
gathers to a scalar loop (see core/cost.py rationale); these tests force
mode='onehot' on CPU to pin its semantics: move application is bit-exact,
the objective matches the gather path to bf16 rounding of the durations
matrix, and the dtype auto-widens to f32 past the 256-integer bf16 bound.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from vrpms_tpu.core import make_instance
from vrpms_tpu.core.cost import (
    CostWeights,
    objective_batch,
    objective_hot_batch,
    onehot_dtype,
    resolve_eval_mode,
)
from vrpms_tpu.core.encoding import is_valid_giant, random_giant_batch
from vrpms_tpu.moves import apply_src_map, random_move_batch, random_src_map
from vrpms_tpu.solvers import SAParams, solve_sa
from tests.test_core_cost import random_instance


@pytest.fixture
def batch(rng):
    inst = random_instance(rng, n=20, v=4)
    giants = random_giant_batch(jax.random.key(0), 32, 19, 4)
    return inst, giants


class TestApplySrcMap:
    def test_onehot_matches_gather_exactly(self, batch):
        _, giants = batch
        src = random_src_map(jax.random.key(1), giants.shape[0], giants.shape[1])
        got = apply_src_map(giants, src, mode="onehot")
        want = apply_src_map(giants, src, mode="gather")
        assert got.dtype == giants.dtype
        assert np.array_equal(np.asarray(got), np.asarray(want))

    def test_gather_matches_per_row_indexing(self, batch):
        _, giants = batch
        src = random_src_map(jax.random.key(2), giants.shape[0], giants.shape[1])
        want = np.take_along_axis(np.asarray(giants), np.asarray(src), axis=1)
        got = apply_src_map(giants, src, mode="gather")
        assert np.array_equal(np.asarray(got), want)

    def test_batched_moves_stay_valid(self, batch):
        _, giants = batch
        for mode in ("gather", "onehot"):
            out = random_move_batch(jax.random.key(3), giants, mode=mode)
            for row in np.asarray(out):
                assert is_valid_giant(row, 19, 4)


class TestObjectiveHot:
    def test_matches_gather_to_bf16_rounding(self, batch):
        inst, giants = batch
        w = CostWeights.make()
        ref = np.asarray(objective_batch(giants, inst, w))
        got = np.asarray(objective_hot_batch(giants, inst, w))
        np.testing.assert_allclose(got, ref, rtol=2e-2)

    def test_capacity_excess_term_included(self, rng):
        # one overloaded vehicle: penalty must dominate the difference
        d = np.ones((4, 4)) - np.eye(4)
        inst = make_instance(
            d, demands=[0, 5, 5, 5], capacities=[6.0, 100.0]
        )
        w = CostWeights.make()
        # all three customers on vehicle 0 (cap 6, load 15 -> excess 9)
        g = jnp.asarray([[0, 1, 2, 3, 0, 0]], dtype=jnp.int32)
        ref = float(objective_batch(g, inst, w)[0])
        got = float(objective_hot_batch(g, inst, w)[0])
        assert abs(got - ref) / ref < 1e-3
        assert got > 9 * float(w.cap)  # the exact penalty survived bf16

    def test_time_windows_use_onehot_scan_path(self, rng):
        # TW instances run the one-hot max-plus-scan path: matches the
        # gather path to bf16 rounding of the durations matrix.
        inst = random_instance(rng, n=8, v=2, tw=True)
        giants = random_giant_batch(jax.random.key(4), 8, 7, 2)
        w = CostWeights.make()
        ref = np.asarray(objective_batch(giants, inst, w))
        got = np.asarray(objective_hot_batch(giants, inst, w))
        np.testing.assert_allclose(got, ref, rtol=2e-2)

    def test_lateness_term_matches_exactly_on_integer_durations(self):
        # integer durations are exact in bf16, so the TW path must agree
        # with the gather path to f32 rounding, lateness included
        d = np.array([[0, 4, 9], [4, 0, 5], [9, 5, 0]], dtype=float)
        inst = make_instance(
            d,
            demands=[0, 1, 1],
            capacities=[10.0],
            ready=[0.0, 0.0, 0.0],
            due=[1e9, 5.0, 6.0],
            service=[0.0, 2.0, 2.0],
        )
        giants = jnp.asarray([[0, 1, 2, 0], [0, 2, 1, 0]], dtype=jnp.int32)
        w = CostWeights.make()
        ref = np.asarray(objective_batch(giants, inst, w))
        got = np.asarray(objective_hot_batch(giants, inst, w))
        np.testing.assert_allclose(got, ref, rtol=1e-6)
        # directional sanity on the path under test: tour 0-1-2-0 is 5
        # late at node 2 (arrive 4+2+5=11 vs due 6); tour 0-2-1-0 is 3
        # late at 2 plus 11 late at 1 — the hot path must rank them so
        assert got[0] < got[1]

    def test_time_dependent_lean_scan_matches_gather(self, rng):
        # the TD hot path must price like the per-leg gather walk
        # _td_eval. T=2 random slices are exactly rank 2, so this
        # exercises the FACTORIZED path (round 3), whose travel times
        # carry the same bf16 table rounding as every other one-hot hot
        # path — hence the bf16-level tolerance. The T=5 test below
        # (td_rank 0, flat-gather fallback) pins f32-exact pricing.
        slices = rng.uniform(1, 50, size=(2, 6, 6))
        inst = make_instance(slices, n_vehicles=2, slice_axis="first")
        assert inst.td_rank == 2
        giants = random_giant_batch(jax.random.key(6), 8, 5, 2)
        w = CostWeights.make()
        ref = np.asarray(objective_batch(giants, inst, w))
        got = np.asarray(objective_hot_batch(giants, inst, w))
        np.testing.assert_allclose(got, ref, rtol=5e-3)

    def test_time_dependent_with_tw_and_makespan_matches_gather(self, rng):
        # TD + time windows + service + per-vehicle shift starts +
        # makespan pricing: every term of the lean-scan path against the
        # reference walk, across many slices
        t, n, v = 5, 9, 3
        slices = rng.uniform(5, 60, size=(t, n, n))
        ready = np.concatenate([[0.0], rng.uniform(0, 120, n - 1)])
        due = ready + rng.uniform(30, 120, n)
        service = rng.integers(0, 10, n).astype(float)
        inst = make_instance(
            slices,
            demands=[0] + [1] * (n - 1),
            capacities=[4.0, 4.0, 4.0],
            ready=ready.tolist(),
            due=due.tolist(),
            service=service.tolist(),
            start_times=[0.0, 30.0, 60.0],
            slice_axis="first",
            slice_minutes=45.0,
        )
        giants = random_giant_batch(jax.random.key(7), 16, n - 1, v)
        w = CostWeights.make(makespan=2.5)
        ref = np.asarray(objective_batch(giants, inst, w))
        got = np.asarray(objective_hot_batch(giants, inst, w))
        np.testing.assert_allclose(got, ref, rtol=2e-5)

    def test_wide_instance_uses_f32(self, rng):
        assert onehot_dtype(256) == jnp.bfloat16
        assert onehot_dtype(300) == jnp.float32
        n = 300  # L = 300 + v > 256 -> f32 one-hots, near-exact objective
        d = rng.uniform(1, 50, size=(n, n))
        inst = make_instance(
            d, demands=rng.uniform(1, 5, n), capacities=[400.0, 400.0]
        )
        giants = random_giant_batch(jax.random.key(5), 4, n - 1, 2)
        w = CostWeights.make()
        ref = np.asarray(objective_batch(giants, inst, w))
        got = np.asarray(objective_hot_batch(giants, inst, w))
        np.testing.assert_allclose(got, ref, rtol=1e-5)


class TestSAOnehotMode:
    def test_solve_sa_onehot_beats_random_and_is_valid(self, rng):
        inst = random_instance(rng, n=15, v=3)
        res = solve_sa(
            inst, key=0, params=SAParams(n_chains=32, n_iters=800), mode="onehot"
        )
        assert is_valid_giant(res.giant, 14, 3)
        w = CostWeights.make()
        rand_costs = objective_batch(
            random_giant_batch(jax.random.key(9), 32, 14, 3), inst, w
        )
        assert float(res.cost) < float(jnp.min(rand_costs))

    def test_resolve_mode(self):
        assert resolve_eval_mode("gather") == "gather"
        assert resolve_eval_mode("onehot") == "onehot"
        assert resolve_eval_mode("pallas") == "pallas"
        # cpu -> gather; tpu -> pallas; other accelerators (gpu) -> onehot
        assert resolve_eval_mode("auto") in ("gather", "pallas", "onehot")
        with pytest.raises(ValueError):
            resolve_eval_mode("bogus")
