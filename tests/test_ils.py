"""Iterated local search: quality, validity, deadline, service wiring."""

import numpy as np
import pytest

from vrpms_tpu.core.encoding import is_valid_giant
from vrpms_tpu.solvers import ILSParams, SAParams, solve_ils, solve_sa
from tests.test_sa import euclidean_cvrp


class TestILS:
    def test_valid_and_not_worse_than_plain_sa(self, rng):
        inst = euclidean_cvrp(rng, n=20, v=4, q=10)
        budget = 2000
        plain = solve_sa(
            inst, key=3, params=SAParams(n_chains=64, n_iters=budget)
        )
        ils = solve_ils(
            inst,
            key=3,
            params=ILSParams(
                rounds=4,
                sa=SAParams(n_chains=64, n_iters=budget // 4),
                pool=8,
            ),
        )
        assert is_valid_giant(ils.giant, 19, 4)
        # polish alone guarantees parity; reseeding usually wins outright
        assert float(ils.cost) <= float(plain.cost) * 1.01 + 1e-3
        assert int(ils.evals) > 0

    def test_deadline_truncates_but_returns_valid(self, rng):
        inst = euclidean_cvrp(rng, n=12, v=3, q=10)
        res = solve_ils(
            inst,
            key=5,
            params=ILSParams(
                rounds=50, sa=SAParams(n_chains=16, n_iters=100_000), pool=4
            ),
            deadline_s=1e-6,
        )
        assert is_valid_giant(res.giant, 11, 3)
        # round 0 always runs (truncated), later rounds are skipped
        assert int(res.evals) < 50 * 16 * 100_000

    def test_deterministic(self, rng):
        inst = euclidean_cvrp(rng, n=10, v=2, q=15)
        p = ILSParams(rounds=2, sa=SAParams(n_chains=16, n_iters=300), pool=4)
        a = solve_ils(inst, key=9, params=p)
        b = solve_ils(inst, key=9, params=p)
        assert float(a.cost) == float(b.cost)
        assert np.array_equal(np.asarray(a.giant), np.asarray(b.giant))

    def test_tw_instance(self, rng):
        from tests.test_core_cost import random_instance

        inst = random_instance(rng, n=9, v=2, tw=True)
        res = solve_ils(
            inst,
            key=1,
            params=ILSParams(rounds=2, sa=SAParams(n_chains=16, n_iters=400), pool=4),
        )
        assert is_valid_giant(res.giant, 8, 2)
