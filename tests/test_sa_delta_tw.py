"""Fused VRPTW delta-step kernel (kernels.sa_delta_tw): interpret-mode
equivalence and state-integrity on CPU.

Strategy: the kernel and the XLA reference compute lateness with
different (both valid) max-plus combination trees, so their costs agree
only to fp tolerance — a single flipped Metropolis accept would fork
trajectories and break exact comparison. The trajectory test therefore
runs ALWAYS-ACCEPT (u = 0), which is decision-independent: after N
steps the kernel's tours must EXACTLY equal N unconditional
move_batch_from_params applications. State integrity then pins the
per-position transform machinery (the legs junction fixes above all):
every maintained array must exactly re-derive from the final tours.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vrpms_tpu.core.cost import (
    CostWeights,
    _legs_hot,
    tw_components_batch,
)
from vrpms_tpu.io.synth import synth_vrptw
from vrpms_tpu.moves import knn_table
from vrpms_tpu.moves.moves import (
    move_batch_from_params,
    presample_move_params,
)
from vrpms_tpu.solvers.sa import SAParams, _pow2_at_least, initial_giants

pytest.importorskip("jax.experimental.pallas")

from vrpms_tpu.kernels import sa_delta_tw as K  # noqa: E402
from vrpms_tpu.kernels.sa_delta import dp_init  # noqa: E402

W = CostWeights.make()


def _setup(n=22, v=4, batch=64, seed=5, knn_k=8):
    inst = synth_vrptw(n, v, seed=seed)
    giants = initial_giants(jax.random.key(1), batch, inst, SAParams(), "onehot")
    b, length = giants.shape
    lhat = _pow2_at_least(length)
    nhat = 128
    knn = knn_table(inst.durations[0], knn_k)
    d_np = np.zeros((nhat, nhat), np.float32)
    d_np[: inst.n_nodes, : inst.n_nodes] = np.asarray(inst.durations[0])
    kf = np.zeros((nhat, knn_k), np.float32)
    kf[: inst.n_nodes] = np.asarray(knn, np.float32)

    gt_t = jnp.zeros((lhat, b), jnp.int32).at[:length].set(giants.T)

    def attr_row(vec):
        row = np.zeros((1, nhat), np.float32)
        row[0, : inst.n_nodes] = np.asarray(vec)
        return jnp.asarray(row)

    dp_t = dp_init(gt_t, attr_row(inst.demands), tile_b=b, interpret=True)
    sv_t = dp_init(
        gt_t, attr_row(inst.service), tile_b=b, exact_f32=True, interpret=True
    )
    rd_t = dp_init(
        gt_t, attr_row(inst.ready), tile_b=b, exact_f32=True, interpret=True
    )
    du_t = dp_init(
        gt_t, attr_row(inst.due), tile_b=b, exact_f32=True, interpret=True
    )
    _, _, legs, _ = _legs_hot(giants, inst)
    lg_t = jnp.zeros((lhat, b), jnp.float32).at[: length - 1].set(legs.T)
    cap0 = float(np.asarray(inst.capacities)[0])
    start0 = float(np.asarray(inst.start_times)[0])
    scal = jnp.asarray(
        [[cap0, float(W.cap), float(W.tw), start0]], jnp.float32
    )
    dist, cape, late, _, _ = tw_components_batch(giants, inst)
    cost0 = (dist + W.cap * cape + W.tw * late)[None]
    return (
        inst, giants, length, lhat, knn,
        jnp.asarray(d_np, jnp.bfloat16), jnp.asarray(kf), scal,
        gt_t, dp_t, sv_t, rd_t, du_t, lg_t, cost0,
    )


def _kernel_state_checks(inst, length, gt_t, dp_t, sv_t, rd_t, du_t, lg_t):
    """Every maintained per-position array must exactly re-derive from
    the final tours — this is what pins the roll/junction-fix algebra."""
    g = np.asarray(gt_t[:length].T)
    for row in g:
        assert sorted(x for x in row if x) == list(
            range(1, inst.n_customers + 1)
        )
    dem = np.asarray(inst.demands)
    sv = np.asarray(inst.service)
    rd = np.asarray(inst.ready)
    du = np.asarray(inst.due)
    np.testing.assert_array_equal(np.asarray(dp_t[:length].T), dem[g])
    np.testing.assert_array_equal(np.asarray(sv_t[:length].T), sv[g])
    np.testing.assert_array_equal(np.asarray(rd_t[:length].T), rd[g])
    np.testing.assert_array_equal(np.asarray(du_t[:length].T), du[g])
    # legs: every entry must be the bf16-table value of its current leg
    legs_ref = np.asarray(_legs_hot(jnp.asarray(g), inst)[2])
    np.testing.assert_array_equal(
        np.asarray(lg_t[: length - 1].T), legs_ref
    )
    # pad legs must stay zero (depot-to-depot)
    assert (np.asarray(lg_t[length - 1 :]) == 0).all()


class TestTwDeltaKernel:
    def test_always_accept_matches_xla_trajectory(self):
        (inst, giants, L, lhat, knn, d_bf16, knn_f, scal,
         gt_t, dp_t, sv_t, rd_t, du_t, lg_t, cost0) = _setup()
        b = giants.shape[0]
        n_steps = 40
        i, r, mt, m, _u = presample_move_params(
            jax.random.key(3), b, L, n_steps, knn.shape[1]
        )
        u0 = jnp.zeros_like(_u)  # always accept: decision-independent
        temps = jnp.full((1, n_steps), 1e6, jnp.float32)
        out = K.delta_tw_block(
            gt_t, dp_t, sv_t, rd_t, du_t, lg_t, cost0, gt_t, cost0,
            i, r, mt, m, u0, temps, d_bf16, knn_f, scal,
            length=L, tile_b=b, has_knn=True, interpret=True,
        )
        g_ref = giants
        for s in range(n_steps):
            g_ref = move_batch_from_params(
                i[s], r[s], mt[s], m[s], g_ref, knn, "gather"
            )
        assert (np.asarray(out[0][:L].T) == np.asarray(g_ref)).all()
        _kernel_state_checks(inst, L, *out[:6])
        # the maintained cost row must track the XLA evaluation of the
        # same tours (fp tolerance: different max-plus trees)
        dist, cape, late, _, _ = tw_components_batch(out[0][:L].T, inst)
        want = np.asarray(dist + W.cap * cape + W.tw * late)
        np.testing.assert_allclose(
            np.asarray(out[6][0]), want, rtol=1e-4, atol=1e-2
        )

    def test_metropolis_never_accepts_worse_at_zero_temp(self):
        (inst, giants, L, lhat, knn, d_bf16, knn_f, scal,
         gt_t, dp_t, sv_t, rd_t, du_t, lg_t, cost0) = _setup(seed=9)
        b = giants.shape[0]
        n_steps = 60
        i, r, mt, m, u = presample_move_params(
            jax.random.key(7), b, L, n_steps, knn.shape[1]
        )
        u = jnp.maximum(u, 1e-9)
        temps = jnp.full((1, n_steps), 1e-6, jnp.float32)
        out = K.delta_tw_block(
            gt_t, dp_t, sv_t, rd_t, du_t, lg_t, cost0, gt_t, cost0,
            i, r, mt, m, u, temps, d_bf16, knn_f, scal,
            length=L, tile_b=b, has_knn=True, interpret=True,
        )
        _kernel_state_checks(inst, L, *out[:6])
        # at ~zero temperature the committed cost is non-increasing, so
        # the final cost row must be <= the initial one (+fp slack)
        assert (
            np.asarray(out[6][0]) <= np.asarray(cost0[0]) + 1e-3
        ).all()
        # and best tracking can only be better than the committed state
        assert (np.asarray(out[8][0]) <= np.asarray(out[6][0]) + 1e-4).all()

    def test_uniform_window_without_knn(self):
        (inst, giants, L, lhat, knn, d_bf16, knn_f, scal,
         gt_t, dp_t, sv_t, rd_t, du_t, lg_t, cost0) = _setup(seed=11)
        b = giants.shape[0]
        n_steps = 25
        i, r, mt, m, _u = presample_move_params(
            jax.random.key(13), b, L, n_steps, 0
        )
        u0 = jnp.zeros_like(_u)
        temps = jnp.full((1, n_steps), 1e6, jnp.float32)
        out = K.delta_tw_block(
            gt_t, dp_t, sv_t, rd_t, du_t, lg_t, cost0, gt_t, cost0,
            i, r, mt, m, u0, temps, d_bf16, knn_f, scal,
            length=L, tile_b=b, has_knn=False, interpret=True,
        )
        g_ref = giants
        for s in range(n_steps):
            g_ref = move_batch_from_params(
                i[s], r[s], mt[s], m[s], g_ref, None, "gather"
            )
        assert (np.asarray(out[0][:L].T) == np.asarray(g_ref)).all()
        _kernel_state_checks(inst, L, *out[:6])


class TestSolveSaDeltaTw:
    def test_solve_level_driver(self, monkeypatch):
        monkeypatch.setenv("VRPMS_DELTA_INTERPRET", "1")
        from vrpms_tpu.core.cost import exact_cost
        from vrpms_tpu.solvers.sa import solve_sa_delta

        inst = synth_vrptw(18, 3, seed=2)
        res = solve_sa_delta(
            inst, key=4, params=SAParams(n_chains=128, n_iters=400)
        )
        row = [int(x) for x in np.asarray(res.giant) if x]
        assert sorted(row) == list(range(1, inst.n_customers + 1))
        # the returned cost is the exact re-evaluation of the champion
        _, want = exact_cost(res.giant, inst, CostWeights.make())
        assert np.isclose(float(res.cost), float(want), rtol=1e-6)

    def test_gate_admits_tw_and_rejects_nonuniform_starts(self):
        from vrpms_tpu.core import make_instance
        from vrpms_tpu.solvers.sa import _delta_supported
        from vrpms_tpu.kernels.sa_delta import _PALLAS_OK

        if not _PALLAS_OK:
            pytest.skip("pallas unavailable")
        inst = synth_vrptw(20, 3, seed=1)
        assert _delta_supported(inst, W, "pallas")
        d = np.asarray(inst.durations[0])
        inst2 = make_instance(
            d,
            demands=np.asarray(inst.demands),
            capacities=np.asarray(inst.capacities).tolist(),
            ready=np.asarray(inst.ready),
            due=np.asarray(inst.due),
            service=np.asarray(inst.service),
            start_times=[0.0, 5.0, 0.0],
        )
        assert not _delta_supported(inst2, W, "pallas")
