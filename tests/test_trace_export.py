"""Fleet observability tests: durable trace export, federated debug
surfaces, per-job timelines, and the fleet rollup (ISSUE 14).

Layers:

  * TestExporterUnit — the exporter contract: off-by-default builds
    nothing, batched rows through the store trace seam, the bounded
    queue drops the OLDEST trace (counted), oversized documents
    degrade (events, then attributes) before dropping, store failures
    count `failed` and never raise into the request path;
  * TestTraceSeam — the store seam on the memory/faulty backends:
    per-(trace, replica) rows union instead of clobbering, list
    summaries merge rows per trace, chaos plans inject, the in-memory
    table stays bounded;
  * TestFederatedHTTP (slow) — the debug endpoints end to end: detail
    merge (local ring wins on span-id conflict), store-down serves
    local-only with `degraded: true` (never a 500), ?scope=fleet,
    ?jobId= job-to-trace resolution, GET /api/jobs/{id}/timeline, the
    /api/debug/fleet rollup, and the VRPMS_TRACE_EXPORT=off guard that
    keeps every pre-export response shape untouched;
  * TestCrossReplicaFederation (slow) — the acceptance gate: a
    two-in-process-replica store-queue job (the test_distqueue
    harness) whose federated read returns spans from BOTH replicas
    under ONE traceId — including the kill-mid-flight case, where the
    reclaimed attempt's dist.execute span carries attempt=2;
  * TestExportChaos — export failures drop cleanly: counters tick,
    requests are unaffected.
"""

import json
import threading
import time
import urllib.error
import urllib.request
import uuid

import numpy as np
import pytest

import store
import store.memory as mem
from service import obs as service_obs
from store.faulty import reset_faults
from store.resilient import reset_resilience
from vrpms_tpu.obs import export, spans
from vrpms_tpu.sched import Replica, Scheduler
from vrpms_tpu.sched.ring import SLOTS, HashRing


def _export_count(outcome: str) -> float:
    return service_obs.TRACE_EXPORT.labels(outcome=outcome).value


@pytest.fixture(autouse=True)
def clean(monkeypatch):
    monkeypatch.setenv("VRPMS_STORE", "memory")
    monkeypatch.delenv("VRPMS_QUEUE", raising=False)
    monkeypatch.delenv("VRPMS_TRACE_EXPORT", raising=False)
    mem.reset()
    reset_faults()
    reset_resilience()  # a prior suite's open breaker must not shed us
    export.reset_exporter()
    export.set_store_factory(None)
    # service.obs wires the observer at import; later imports of other
    # modules must never have left a stale one behind
    export.set_observer(
        lambda outcome, n: service_obs.TRACE_EXPORT.labels(
            outcome=outcome
        ).inc(n)
    )
    spans.reset_ring()
    yield
    export.reset_exporter()
    export.set_store_factory(None)
    mem.reset()
    reset_faults()
    spans.reset_ring()


def _make_trace(tid=None, root_name="POST /api/vrp/sa", n_children=1):
    t = spans.Trace(trace_id=tid)
    root = t.span(root_name)
    root.set(replica="local-test")
    for i in range(n_children):
        child = t.span("solve", parent_id=root.span_id)
        child.set(jobId=f"j{i}")
        child.end()
    root.end()
    return t


def _wait(cond, timeout=10.0, every=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(every)
    return cond()


# ---------------------------------------------------------------------------
# Exporter unit layer
# ---------------------------------------------------------------------------


class TestExporterUnit:
    def test_off_by_default_builds_nothing_and_writes_nothing(self):
        t = _make_trace()
        t.finish()
        assert export._exporter is None  # no exporter constructed
        assert mem._tables["trace_spans"] == {}
        assert spans.ring_get(t.trace_id) is not None  # ring untouched

    def test_export_round_trip(self, monkeypatch):
        monkeypatch.setenv("VRPMS_TRACE_EXPORT", "on")
        ok0 = _export_count("ok")
        t = _make_trace(n_children=2)
        t.finish()
        assert export.flush(10.0)
        db = store.get_database("vrp", None)
        rows = db.get_trace_spans(t.trace_id)
        assert len(rows) == 1
        row = rows[0]
        assert row["spans"] == 3
        assert row["status"] == "ok"
        assert row["root"] == "POST /api/vrp/sa"
        assert row["started_at"] == pytest.approx(t.start_ts)
        names = [s["name"] for s in row["doc"]["spans"]]
        assert names == ["POST /api/vrp/sa", "solve", "solve"]
        assert row["doc"]["replica"] == row["replica"]
        assert _export_count("ok") - ok0 == 3
        summaries = db.list_traces(10)
        assert [s["traceId"] for s in summaries] == [t.trace_id]
        assert summaries[0]["spans"] == 3

    def test_empty_traces_are_not_offered(self, monkeypatch):
        monkeypatch.setenv("VRPMS_TRACE_EXPORT", "on")
        t = spans.Trace()
        t.finish()  # no spans: no evidence
        assert export._exporter is None

    def test_queue_overflow_drops_oldest(self, monkeypatch):
        monkeypatch.setenv("VRPMS_TRACE_EXPORT", "on")
        gate = threading.Event()
        written: list = []

        class SlowDB:
            def put_trace_spans(self, rows):
                gate.wait(10)
                written.extend(rows)
                return True

        export.set_store_factory(lambda: SlowDB())
        dropped0 = _export_count("dropped")
        exp = export.TraceExporter(queue_cap=2, batch=1, flush_s=0.01)
        try:
            traces = [_make_trace(n_children=0) for _ in range(5)]
            for t in traces:
                exp.offer(t)
            # flusher holds one in flight; cap 2 → at least 2 dropped
            assert _wait(
                lambda: _export_count("dropped") - dropped0 >= 2
            ), _export_count("dropped")
        finally:
            gate.set()
            exp.stop(2.0)
        assert written  # the survivors were still written

    def test_oversized_doc_degrades_then_drops(self):
        t = spans.Trace()
        s = t.span("solve")
        s.set(huge="x" * (export.MAX_ROW_BYTES + 1024))
        for i in range(10):
            s.event("block", i=i)
        s.end()
        row = export.serialize_trace(t, "r1")
        # events went first, then the oversized attributes; the doc
        # survives, marked truncated
        assert row is not None
        doc_span = row["doc"]["spans"][0]
        assert "events" not in doc_span and "attributes" not in doc_span
        assert row["doc"]["truncated"] is True
        # a skeleton that is itself too big has nothing left to shed
        t2 = spans.Trace()
        t2.span("x" * (export.MAX_ROW_BYTES + 1024)).end()
        assert export.serialize_trace(t2, "r1") is None

    def test_store_failure_counts_failed_and_never_raises(
        self, monkeypatch
    ):
        monkeypatch.setenv("VRPMS_TRACE_EXPORT", "on")
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        failed0 = _export_count("failed")
        t = _make_trace()
        t.finish()  # must not raise
        assert export.flush(10.0)
        assert _export_count("failed") - failed0 == 2
        assert export.queue_depth() == 0

    def test_replica_identity_prefers_provider(self):
        assert export.replica_identity()  # never empty
        export.set_replica_provider(lambda: "prov-1")
        try:
            assert export.replica_identity() == "prov-1"
        finally:
            from service.jobs import replica_id

            export.set_replica_provider(replica_id)


# ---------------------------------------------------------------------------
# Store trace seam
# ---------------------------------------------------------------------------


class TestTraceSeam:
    def _row(self, tid, replica, names, started=1000.0):
        return {
            "trace_id": tid,
            "replica": replica,
            "started_at": started,
            "duration_ms": 5.0,
            "status": "ok",
            "root": names[0],
            "spans": len(names),
            "doc": {
                "traceId": tid,
                "startedAt": started,
                "durationMs": 5.0,
                "status": "ok",
                "replica": replica,
                "spans": [
                    {
                        "name": n,
                        "spanId": uuid.uuid4().hex[:16],
                        "parentId": None,
                        "startMs": 0.0,
                        "durationMs": 1.0,
                        "status": "ok",
                    }
                    for n in names
                ],
            },
        }

    def test_rows_union_per_replica(self):
        db = store.get_database("vrp", None)
        tid = uuid.uuid4().hex
        assert db.put_trace_spans([self._row(tid, "a", ["http"])])
        assert db.put_trace_spans(
            [self._row(tid, "b", ["dist.execute", "solve"], started=1000.5)]
        )
        rows = db.get_trace_spans(tid)
        assert {r["replica"] for r in rows} == {"a", "b"}
        # one summary per trace, rows merged: spans summed, both
        # replicas named, duration spanning the earliest start to the
        # latest end
        (summary,) = db.list_traces(10)
        assert summary["traceId"] == tid
        assert summary["spans"] == 3
        assert sorted(summary["replicas"]) == ["a", "b"]
        assert summary["durationMs"] == pytest.approx(505.0)

    def test_same_replica_overwrites_not_duplicates(self):
        db = store.get_database("vrp", None)
        tid = uuid.uuid4().hex
        db.put_trace_spans([self._row(tid, "a", ["http"])])
        db.put_trace_spans([self._row(tid, "a", ["http", "solve"])])
        rows = db.get_trace_spans(tid)
        assert len(rows) == 1 and rows[0]["spans"] == 2

    def test_memory_table_stays_bounded(self):
        db = store.get_database("vrp", None)
        cap = mem._InMemoryMixin.MAX_TRACE_ROWS
        rows = [
            self._row(uuid.uuid4().hex, "a", ["x"]) for _ in range(40)
        ]
        mem._tables["trace_spans"].update({
            (f"t{i}", "a"): {"trace_id": f"t{i}", "replica": "a"}
            for i in range(cap)
        })
        db.put_trace_spans(rows)
        assert len(mem._tables["trace_spans"]) == cap

    def test_faulty_plan_injects(self, monkeypatch):
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        db = store.get_database("vrp", None)
        tid = uuid.uuid4().hex
        assert db.put_trace_spans([self._row(tid, "a", ["x"])]) is False
        assert db.get_trace_spans(tid) is None
        assert db.list_traces(5) is None

    def test_replica_info_registry(self):
        qs = store.get_queue_store()
        qs.register_replica("r1", 60.0, {"inflight": 3})
        qs.register_replica("r2", 60.0)
        infos = qs.replica_infos()
        assert infos["r1"] == {"inflight": 3}
        assert infos["r2"] == {}
        # a doc-less re-beat keeps the last doc (mixed fleets)
        qs.register_replica("r1", 60.0)
        assert qs.replica_infos()["r1"] == {"inflight": 3}
        assert sorted(qs.replicas()) == ["r1", "r2"]


# ---------------------------------------------------------------------------
# HTTP layer
# ---------------------------------------------------------------------------


def _seed_dataset(key, n, seed=11):
    rng = np.random.default_rng(seed)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        key, [{"id": i, "demand": 2 if i else 0} for i in range(n)]
    )
    mem.seed_durations(key, d.tolist())


def _solve_content(key, n, seed=1):
    return {
        "problem": "vrp",
        "algorithm": "sa",
        "solutionName": f"obs-{key}",
        "solutionDescription": "t",
        "locationsKey": key,
        "durationsKey": key,
        "capacities": [2 * n] * 3,
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": seed,
        "iterationCount": 200,
        "populationSize": 8,
    }


@pytest.fixture(scope="module")
def server():
    import os

    os.environ["VRPMS_STORE"] = "memory"
    from service import jobs as jobs_mod
    from service.app import serve

    jobs_mod.shutdown_scheduler()
    srv = serve(port=0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    jobs_mod.shutdown_scheduler()


def _post(base, path, body):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=300) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=60) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _poll(base, job_id, timeout=120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status, resp = _get(base, f"/api/jobs/{job_id}")
        assert status == 200, resp
        if resp["job"]["status"] in ("done", "failed"):
            return resp["job"]
        time.sleep(0.05)
    raise AssertionError(f"job {job_id} never finished")


class TestFederatedHTTP:
    @pytest.fixture(autouse=True)
    def env(self, server, monkeypatch):
        from service import jobs as jobs_mod

        monkeypatch.setenv("VRPMS_TRACE_EXPORT", "on")
        _seed_dataset("fed7", 7)
        yield
        jobs_mod.shutdown_scheduler()

    def _store_row(self, tid, replica, names, started, span_ids=None):
        span_ids = span_ids or [uuid.uuid4().hex[:16] for _ in names]
        doc = {
            "traceId": tid,
            "startedAt": started,
            "durationMs": 3.0,
            "status": "ok",
            "replica": replica,
            "spans": [
                {
                    "name": n,
                    "spanId": sid,
                    "parentId": None,
                    "startMs": float(i),
                    "durationMs": 1.0,
                    "status": "ok",
                    "events": [{"name": "job.started", "offsetMs": 0.5}],
                }
                for i, (n, sid) in enumerate(zip(names, span_ids))
            ],
        }
        return {
            "trace_id": tid,
            "replica": replica,
            "started_at": started,
            "duration_ms": 3.0,
            "status": "ok",
            "root": names[0],
            "spans": len(names),
            "doc": doc,
        }

    def test_detail_federates_and_local_wins(self, server):
        t = _make_trace()
        t.finish()
        local_solve = [
            s for s in t.to_dict()["spans"] if s["name"] == "solve"
        ][0]
        db = store.get_database("vrp", None)
        # another replica exported its half — including a CONFLICTING
        # copy of the local solve span id, which must lose to the ring
        db.put_trace_spans([
            self._store_row(
                t.trace_id, "replica-b",
                ["dist.execute", "bogus-copy"],
                started=t.start_ts + 0.002,
                span_ids=[uuid.uuid4().hex[:16], local_solve["spanId"]],
            ),
        ])
        status, resp = _get(server, f"/api/debug/traces/{t.trace_id}")
        assert status == 200, resp
        trace = resp["trace"]
        assert "degraded" not in resp
        names = [s["name"] for s in trace["spans"]]
        assert "dist.execute" in names
        assert "bogus-copy" not in names  # the local span id won
        assert "solve" in names
        assert len(trace["replicas"]) == 2
        # the remote span's offset was rebased onto the earliest start
        dist = [s for s in trace["spans"] if s["name"] == "dist.execute"][0]
        assert dist["startMs"] >= 2.0
        assert dist["replica"] == "replica-b"
        # ...and its EVENTS were rebased onto the same merged clock (an
        # un-shifted offset would sort the event before its own span)
        (ev,) = dist["events"]
        assert ev["offsetMs"] == pytest.approx(2.5, abs=0.3)

    def test_detail_store_down_degrades_local_only(
        self, server, monkeypatch
    ):
        t = _make_trace()
        t.finish()
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        status, resp = _get(server, f"/api/debug/traces/{t.trace_id}")
        assert status == 200, resp
        assert resp["degraded"] is True
        assert [s["name"] for s in resp["trace"]["spans"]] == [
            "POST /api/vrp/sa", "solve",
        ]

    def test_detail_unknown_is_404_never_500(self, server, monkeypatch):
        status, resp = _get(server, f"/api/debug/traces/{uuid.uuid4().hex}")
        assert status == 404 and not resp["success"]
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        status, resp = _get(server, f"/api/debug/traces/{uuid.uuid4().hex}")
        assert status == 404, resp
        assert resp["degraded"] is True

    def test_export_off_keeps_surfaces_byte_identical(
        self, server, monkeypatch
    ):
        monkeypatch.setenv("VRPMS_TRACE_EXPORT", "off")
        t = _make_trace()
        t.finish()
        # a store row exists for the trace — off means it is NEVER read
        db = store.get_database("vrp", None)
        db.put_trace_spans([
            self._store_row(t.trace_id, "replica-b", ["dist.execute"],
                            started=t.start_ts),
        ])
        status, resp = _get(server, f"/api/debug/traces/{t.trace_id}")
        assert status == 200
        assert set(resp) == {"success", "trace", "requestId"}
        assert resp["trace"] == t.to_dict()  # no merge keys, no replicas
        status, resp = _get(server, "/api/debug/traces")
        assert status == 200
        assert set(resp) == {
            "success", "tracing", "capacity", "traces", "requestId",
        }

    def test_fleet_scope_lists_exported_summaries(
        self, server, monkeypatch
    ):
        for _ in range(2):
            _make_trace().finish()
        assert export.flush(10.0)
        status, resp = _get(server, "/api/debug/traces?scope=fleet")
        assert status == 200, resp
        assert resp["scope"] == "fleet"
        assert len(resp["traces"]) == 2
        assert all(t["replicas"] for t in resp["traces"])
        # store down: local ring serves, marked degraded
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        status, resp = _get(server, "/api/debug/traces?scope=fleet")
        assert status == 200, resp
        assert resp["degraded"] is True and resp["scope"] == "local"
        assert len(resp["traces"]) == 2  # the ring still has them

    def test_bad_scope_is_400(self, server):
        status, resp = _get(server, "/api/debug/traces?scope=galaxy")
        assert status == 400 and not resp["success"]

    def test_jobid_filter_resolves_trace(self, server):
        status, resp = _post(server, "/api/jobs", _solve_content("fed7", 7))
        assert status == 202, resp
        job = _poll(server, resp["jobId"])
        assert job["status"] == "done"
        assert job["traceId"]
        status, resp = _get(
            server, f"/api/debug/traces?jobId={job['id']}"
        )
        assert status == 200, resp
        assert resp["resolvedTraceId"] == job["traceId"]
        assert resp["traces"] and (
            resp["traces"][0]["traceId"] == job["traceId"]
        )

    def test_jobid_unknown_is_404(self, server):
        status, resp = _get(server, "/api/debug/traces?jobId=nope")
        assert status == 404 and not resp["success"]

    def test_timeline_tells_the_job_story(self, server):
        status, resp = _post(
            server, "/api/jobs", _solve_content("fed7", 7, seed=5)
        )
        assert status == 202, resp
        job = _poll(server, resp["jobId"])
        assert job["status"] == "done"
        status, resp = _get(server, f"/api/jobs/{job['id']}/timeline")
        assert status == 200, resp
        assert resp["traceId"] == job["traceId"]
        events = resp["timeline"]
        kinds = [e["event"] for e in events]
        assert kinds[0] == "submitted"
        assert "solve" in kinds
        assert kinds[-1] == "done" or "done" in kinds
        # ordered: every clocked event is monotone
        clocked = [e["atMs"] for e in events if e["atMs"] is not None]
        assert clocked == sorted(clocked)
        solve_ev = [e for e in events if e["event"] == "solve"][0]
        assert "replica" in solve_ev and "ran" in solve_ev["detail"]
        # incumbents from the persisted progress profile ride along
        assert any(e["event"] == "incumbent" for e in events) or (
            job.get("progress") is None
        )

    def test_timeline_unknown_job_is_404(self, server):
        status, resp = _get(server, "/api/jobs/nope/timeline")
        assert status == 404 and not resp["success"]

    def test_fleet_endpoint_local_mode(self, server):
        status, resp = _get(server, "/api/debug/fleet")
        assert status == 200, resp
        fleet = resp["fleet"]
        assert fleet["queue"] == "local"
        (self_info,) = [
            r for r in fleet["replicas"].values() if r.get("self")
        ]
        assert isinstance(self_info["tiersWarmed"], list)
        assert self_info["replicaId"] == fleet["generatedBy"]

    def test_fleet_endpoint_aggregates_heartbeat_docs(
        self, server, monkeypatch
    ):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        qs = store.get_queue_store()
        qs.register_replica(
            "peer-1", 60.0,
            {"inflight": 3, "tiersWarmed": ["vrp:8x8x3"], "queued": 1},
        )
        status, resp = _get(server, "/api/debug/fleet")
        assert status == 200, resp
        fleet = resp["fleet"]
        assert fleet["queue"] == "store"
        peer = fleet["replicas"]["peer-1"]
        assert peer["inflight"] == 3
        assert peer["tiersWarmed"] == ["vrp:8x8x3"]
        assert not peer.get("self")
        assert any(
            r.get("self") for r in fleet["replicas"].values()
        )
        assert fleet.get("sharedDepth") == 0

    def test_fleet_endpoint_store_down_degrades(
        self, server, monkeypatch
    ):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_STORE", "faulty:down")
        monkeypatch.setenv("VRPMS_DEPTH_MEMO_MS", "0")
        status, resp = _get(server, "/api/debug/fleet")
        assert status == 200, resp
        assert resp["degraded"] is True
        # the local replica's live view still serves
        assert any(
            r.get("self") for r in resp["fleet"]["replicas"].values()
        )


# ---------------------------------------------------------------------------
# Cross-replica federation (the acceptance gate)
# ---------------------------------------------------------------------------


def _service_replica(rid, runner=None, **kw):
    from service import jobs as jobs_mod

    sched = Scheduler(
        runner if runner is not None else jobs_mod._runner,
        queue_limit=64,
        window_s=0.005,
        max_batch=8,
        on_event=jobs_mod._on_event,
        watchdog_s=0,
    )
    defaults = dict(
        lease_s=1.0, poll_s=0.01, heartbeat_s=0.1, reclaim_s=0.05,
        vnodes=16,
    )
    defaults.update(kw)
    rep = Replica(
        store.get_queue_store(),
        rid,
        materialize=lambda e: jobs_mod._materialize_entry(e, rid),
        submit=lambda job: sched.submit(
            job, backend=job.payload.get("backend") or "default"
        ),
        complete=jobs_mod._dist_complete,
        dead=jobs_mod._dist_dead,
        **defaults,
    )
    rep._test_scheduler = sched
    return rep


class TestCrossReplicaFederation:
    def _entry(self, job_id, tid, slot, content, bucket="fed9-tier"):
        return {
            "id": job_id,
            "slot": slot,
            "bucket": bucket,
            "time_limit": None,
            "submitted_at": time.time(),
            "payload": {
                "content": content,
                "requestId": f"req-{job_id}",
                "problem": "vrp",
                "algorithm": "sa",
                "traceparent": f"00-{tid}-{uuid.uuid4().hex[:16]}-01",
            },
        }

    def _submit_side_trace(self, tid):
        """The submitting replica's half of the trace: the HTTP root it
        records before the 202, finished (and exported) there."""
        t = spans.Trace(trace_id=tid)
        root = t.span("POST /api/jobs")
        t.span("parse", parent_id=root.span_id).end()
        root.end()
        t.finish()
        return t

    def test_federated_read_spans_both_replicas_incl_attempt2(
        self, monkeypatch
    ):
        monkeypatch.setenv("VRPMS_QUEUE", "store")
        monkeypatch.setenv("VRPMS_TRACE_EXPORT", "on")
        _seed_dataset("fed9", 9)
        qs = store.get_queue_store()

        block = threading.Event()

        def blocked_runner(jobs):
            block.wait(timeout=600)  # a wedged box: never completes

        victim = _service_replica(
            "victim", runner=blocked_runner, lease_s=0.8, steal=False
        )
        rescuer = _service_replica("rescuer", lease_s=0.8, steal=False)
        qs.register_replica("victim", 60.0)
        qs.register_replica("rescuer", 60.0)
        ring = HashRing(["victim", "rescuer"], vnodes=16)
        victim_slot = next(
            s for s in range(0, SLOTS, 191) if ring.owner(s) == "victim"
        )
        rescuer_slot = next(
            s for s in range(0, SLOTS, 191) if ring.owner(s) == "rescuer"
        )
        # job A: claimed by the victim, which dies mid-flight — the
        # rescuer reclaims it at attempt 2. job B: solved directly by
        # the rescuer at attempt 1.
        tid_a, tid_b = uuid.uuid4().hex, uuid.uuid4().hex
        entry_a = self._entry(
            uuid.uuid4().hex[:16], tid_a, victim_slot,
            _solve_content("fed9", 9, seed=31),
        )
        # a DISTINCT ring token: claim-K batching fills mates by token
        # from the whole queue, so sharing one would let the victim's
        # batch claim sweep job B up too
        entry_b = self._entry(
            uuid.uuid4().hex[:16], tid_b, rescuer_slot,
            _solve_content("fed9", 9, seed=32), bucket="fed9-tier-b",
        )
        # the submit side's half of both traces, exported from "here"
        self._submit_side_trace(tid_a)
        self._submit_side_trace(tid_b)
        qs.enqueue(entry_a)
        qs.enqueue(entry_b)
        try:
            victim.start()
            rescuer.start()
            assert _wait(lambda: victim.inflight() >= 1, timeout=20)
            victim.kill()

            db = store.get_database("vrp", None)

            def both_done():
                for e in (entry_a, entry_b):
                    rec = db.get_job_seed(e["id"])
                    if rec is None or rec.get("status") != "done":
                        return False
                return True

            assert _wait(both_done, timeout=120), {
                e["id"]: db.get_job_seed(e["id"])
                for e in (entry_a, entry_b)
            }
        finally:
            block.set()
            victim.kill()
            rescuer.stop()
            victim._test_scheduler.shutdown(timeout=0.2)
            rescuer._test_scheduler.shutdown(timeout=5.0)
        assert export.flush(15.0)

        from service.debug import merge_trace

        my_rid = export.replica_identity()
        for tid, attempt in ((tid_a, 2), (tid_b, 1)):
            rows = db.get_trace_spans(tid)
            assert rows is not None and rows, tid
            merged = merge_trace(tid, spans.ring_get(tid), rows)
            assert merged is not None
            # spans from BOTH replicas under ONE traceId: the submit
            # side's HTTP root + the executing replica's claim-side
            # spans
            assert my_rid in merged["replicas"], merged["replicas"]
            assert "rescuer" in merged["replicas"], merged["replicas"]
            names = [s["name"] for s in merged["spans"]]
            assert "POST /api/jobs" in names
            assert "dist.execute" in names
            assert "solve" in names
            dist = [
                s for s in merged["spans"] if s["name"] == "dist.execute"
            ]
            assert max(
                s.get("attributes", {}).get("attempt", 1) for s in dist
            ) == attempt, (tid, dist)
            # every claim-side span is attributed to the replica that
            # recorded it
            solve = [s for s in merged["spans"] if s["name"] == "solve"]
            assert all(s.get("replica") == "rescuer" for s in solve)


# ---------------------------------------------------------------------------
# Chaos: export failures drop cleanly
# ---------------------------------------------------------------------------


class TestExportChaos:
    def test_export_failure_never_touches_requests(
        self, server, monkeypatch
    ):
        from service import jobs as jobs_mod

        jobs_mod.shutdown_scheduler()
        monkeypatch.setenv("VRPMS_TRACE_EXPORT", "on")
        # writes down: the exporter's batch write fails every time,
        # while the request path's reads (locations/durations) work
        monkeypatch.setenv("VRPMS_STORE", "faulty:down;ops=writes")
        _seed_dataset("chaos7", 7)
        failed0 = _export_count("failed")
        for seed in range(3):
            status, resp = _post(
                server, "/api/vrp/sa",
                _solve_content("chaos7", 7, seed=seed),
            )
            assert status == 200, resp
            assert resp["success"] is True
        assert export.flush(15.0)
        assert _export_count("failed") - failed0 > 0
        assert export.queue_depth() == 0
        jobs_mod.shutdown_scheduler()
