"""Unit tests for the resilience layer (ISSUE 3): the fault-plan DSL,
the retry/backoff + circuit-breaker state machine, degraded-mode
cache/journal fallbacks of the resilient store wrapper, and scheduler
worker supervision (crash -> requeue-once -> clean second-crash
failure). No HTTP, no jax — tests/test_chaos.py covers end-to-end.
"""

import threading
import time

import pytest

# the supervision tests kill worker threads ON PURPOSE (SystemExit in a
# stub runner) — the thread-death is the scenario, not a test leak
pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)

from store.base import Database, DatabaseVRP
from store.resilient import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    FallbackStore,
    ResilientDatabaseVRP,
    StoreUnavailable,
    WriteJournal,
    backoff_s,
    reset_resilience,
)
from vrpms_tpu.testing.faults import FaultInjector, StoreFault, parse_plan


@pytest.fixture(autouse=True)
def fresh_state(monkeypatch):
    # fast policy defaults for every test; individual tests override
    monkeypatch.setenv("VRPMS_STORE_DEADLINE_S", "1.0")
    monkeypatch.setenv("VRPMS_STORE_RETRIES", "2")
    monkeypatch.setenv("VRPMS_STORE_BACKOFF_S", "0.001")
    monkeypatch.setenv("VRPMS_CB_FAILURES", "3")
    monkeypatch.setenv("VRPMS_CB_RESET_S", "0.15")
    reset_resilience()
    yield
    reset_resilience()


# ---------------------------------------------------------------------------
# Fault-plan DSL
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def test_parses_full_plan(self):
        p = parse_plan("fail=3; latency=0.01, jitter=0.02;rate=0.5;"
                       "ops=reads;seed=7;hang=1.5")
        assert p.fail_n == 3
        assert p.latency_s == 0.01
        assert p.jitter_s == 0.02
        assert p.rate == 0.5
        assert p.ops == "reads"
        assert p.seed == 7
        assert p.hang_s == 1.5
        assert not p.down

    def test_empty_and_down(self):
        assert parse_plan("") == parse_plan(None)
        assert parse_plan("down").down is True

    @pytest.mark.parametrize(
        "bad", ["nonsense", "fail=x", "rate=1.5", "ops=sometimes",
                "latency=-1", "down=maybe"]
    )
    def test_bad_tokens_raise(self, bad):
        with pytest.raises(ValueError):
            parse_plan(bad)

    def test_fail_n_then_succeed(self):
        inj = FaultInjector(parse_plan("fail=3"))
        for _ in range(3):
            with pytest.raises(StoreFault):
                inj.apply("read")
        inj.apply("read")  # 4th call clean
        assert inj.faults == 3 and inj.calls == 4

    def test_ops_filter(self):
        inj = FaultInjector(parse_plan("down;ops=writes"))
        inj.apply("read")  # unmatched: no fault, not even counted
        with pytest.raises(StoreFault):
            inj.apply("write")
        assert inj.calls == 1

    def test_rate_is_seeded_and_approximate(self):
        inj = FaultInjector(parse_plan("rate=0.3;seed=11"))
        faults = 0
        for _ in range(400):
            try:
                inj.apply("read")
            except StoreFault:
                faults += 1
        assert 0.2 < faults / 400 < 0.4


# ---------------------------------------------------------------------------
# Circuit breaker + backoff
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


class TestCircuitBreaker:
    def test_opens_at_threshold_and_sheds(self):
        clk = FakeClock()
        cb = CircuitBreaker(threshold=3, reset_s=10.0, clock=clk)
        assert cb.state == CLOSED
        assert not cb.record_failure()
        assert not cb.record_failure()
        assert cb.record_failure()  # the opening failure reports True
        assert cb.state == OPEN
        assert not cb.allow()
        # straggler failures while open don't extend the window
        clk.now = 5.0
        assert not cb.record_failure()
        clk.now = 10.0
        assert cb.state == HALF_OPEN

    def test_half_open_admits_exactly_one_probe(self):
        clk = FakeClock()
        cb = CircuitBreaker(threshold=1, reset_s=1.0, clock=clk)
        cb.record_failure()
        clk.now = 1.0
        assert cb.allow()  # the probe
        assert not cb.allow()  # everyone else still shed
        cb.record_failure()  # probe failed -> re-open, window restarts
        assert cb.state == OPEN
        assert not cb.allow()
        clk.now = 2.0
        assert cb.allow()
        assert cb.record_success()  # recovery reported (journal replay cue)
        assert cb.state == CLOSED
        assert cb.allow() and cb.allow()  # closed admits everyone

    def test_success_resets_failure_count(self):
        cb = CircuitBreaker(threshold=2, reset_s=1.0, clock=FakeClock())
        cb.record_failure()
        assert not cb.record_success()  # was closed: not a "recovery"
        cb.record_failure()
        assert cb.state == CLOSED  # count restarted after the success


class TestBackoff:
    def test_jittered_exponential_within_bounds(self):
        for attempt in range(4):
            for _ in range(50):
                v = backoff_s(attempt, 0.1)
                assert 0.5 * 0.1 * 2**attempt <= v < 1.5 * 0.1 * 2**attempt

    def test_capped(self):
        assert backoff_s(30, 1.0) < 2.0 * 1.5 + 1e-9


# ---------------------------------------------------------------------------
# Resilient store wrapper
# ---------------------------------------------------------------------------


class ScriptedDB(DatabaseVRP):
    """Inner backend whose primitives fail `fail_reads`/`fail_writes`
    times (or forever with -1), optionally sleeping first."""

    def __init__(self, fail_reads=0, fail_writes=0, sleep_s=0.0):
        super().__init__(None)
        self.fail_reads = fail_reads
        self.fail_writes = fail_writes
        self.sleep_s = sleep_s
        self.read_attempts = 0
        self.write_attempts = 0
        self.jobs: dict = {}
        self.solutions: list = []

    def _maybe_fail(self, kind):
        if self.sleep_s:
            time.sleep(self.sleep_s)
        if kind == "read":
            self.read_attempts += 1
            if self.fail_reads == -1 or self.read_attempts <= self.fail_reads:
                raise RuntimeError("scripted read failure")
        else:
            self.write_attempts += 1
            if self.fail_writes == -1 or self.write_attempts <= self.fail_writes:
                raise RuntimeError("scripted write failure")

    def _fetch_row(self, table, row_id):
        self._maybe_fail("read")
        return {"id": row_id, "locations": ["L"], "matrix": [[0]]}

    def _owner_email(self):
        self._maybe_fail("read")
        return None

    def _fetch_job(self, job_id):
        self._maybe_fail("read")
        return self.jobs.get(str(job_id))

    def _upsert_job(self, job_id, record):
        self._maybe_fail("write")
        self.jobs[str(job_id)] = {"id": job_id, "record": record}

    def _insert_solution(self, data):
        self._maybe_fail("write")
        self.solutions.append(data)
        return data


def resilient(inner, kind="testkind"):
    return ResilientDatabaseVRP(inner, kind)


class TestResilientReads:
    def test_retries_then_succeeds(self):
        inner = ScriptedDB(fail_reads=2)
        db = resilient(inner)
        errors: list = []
        assert db.get_locations_by_id(1, errors) == ["L"]
        assert not errors
        assert inner.read_attempts == 3  # 2 failures + 1 success
        assert db.degraded is False

    def test_exhausted_retries_without_cache_is_an_error(self):
        inner = ScriptedDB(fail_reads=-1)
        db = resilient(inner)
        errors: list = []
        assert db.get_locations_by_id(1, errors) is None
        assert errors and errors[0]["what"] == "Database read error"
        assert inner.read_attempts == 3  # retries bounded

    def test_deadline_bounds_a_hung_call(self, monkeypatch):
        monkeypatch.setenv("VRPMS_STORE_DEADLINE_S", "0.1")
        monkeypatch.setenv("VRPMS_STORE_RETRIES", "0")
        inner = ScriptedDB(sleep_s=2.0)
        db = resilient(inner, kind="hungkind")
        errors: list = []
        t0 = time.monotonic()
        assert db.get_locations_by_id(1, errors) is None
        assert time.monotonic() - t0 < 1.0  # never the full 2s hang
        assert "deadline" in errors[0]["reason"]

    def test_deadline_bounds_the_whole_read_across_retries(self, monkeypatch):
        # retries must NOT multiply the hang bound: attempts share one
        # deadline budget, so a hung backend costs one deadline total
        monkeypatch.setenv("VRPMS_STORE_DEADLINE_S", "0.2")
        monkeypatch.setenv("VRPMS_STORE_RETRIES", "3")
        inner = ScriptedDB(sleep_s=5.0)
        db = resilient(inner, kind="hungkind2")
        errors: list = []
        t0 = time.monotonic()
        assert db.get_locations_by_id(1, errors) is None
        assert time.monotonic() - t0 < 0.2 * 2 + 0.3  # ~one budget, not 4

    def test_circuit_opens_then_cache_serves_degraded(self):
        inner = ScriptedDB()
        db = resilient(inner)
        errors: list = []
        assert db.get_locations_by_id(7, errors) == ["L"]  # warms cache
        inner.fail_reads = -1
        # threshold 3, retries 2: one request's 3 failed attempts open it
        db2 = resilient(inner)
        assert db2.get_locations_by_id(7, errors) == ["L"]
        assert db2.degraded is True
        attempts = inner.read_attempts
        # circuit now open: the next read sheds without touching inner
        db3 = resilient(inner)
        assert db3.get_locations_by_id(7, errors) == ["L"]
        assert db3.degraded is True
        assert inner.read_attempts == attempts

    def test_open_circuit_without_cache_raises_unavailable(self):
        inner = ScriptedDB(fail_reads=-1)
        db = resilient(inner)
        errors: list = []
        db.get_locations_by_id(1, errors)  # opens the circuit
        with pytest.raises(StoreUnavailable):
            db._read("_fetch_row", ("locations", 99), cache_key=None)


class TestResilientWrites:
    def test_writes_are_at_most_once_then_journaled(self):
        inner = ScriptedDB(fail_writes=-1)
        db = resilient(inner)
        assert db.save_job("j1", {"status": "queued"}) is True
        assert inner.write_attempts == 1  # no inline write retry
        assert db.degraded is True
        # degraded read-your-writes: the spooled record is visible
        errors: list = []
        inner.fail_reads = -1
        rec = resilient(inner).get_job("j1", errors)
        assert rec == {"status": "queued"}

    def test_journal_replays_on_recovery(self, monkeypatch):
        monkeypatch.setenv("VRPMS_CB_RESET_S", "0.05")
        inner = ScriptedDB(fail_writes=1, fail_reads=2)
        db = resilient(inner)
        db.save_job("a", {"s": 1})   # spooled (write 1 fails; failure #1)
        errors: list = []
        db.get_locations_by_id(1, errors)  # failures #2-3 -> circuit opens
        db2 = resilient(inner)
        db2.save_job("b", {"s": 2})  # circuit open -> straight to journal
        assert inner.jobs == {}
        time.sleep(0.08)  # past reset_s: next call is the half-open probe
        assert resilient(inner).get_locations_by_id(1, errors) == ["L"]
        deadline = time.monotonic() + 2.0  # replay runs in the background
        while set(inner.jobs) != {"a", "b"} and time.monotonic() < deadline:
            time.sleep(0.01)
        assert set(inner.jobs) == {"a", "b"}  # journal replayed in order
        assert inner.jobs["b"]["record"] == {"s": 2}

    def test_direct_write_supersedes_spooled_version(self, monkeypatch):
        # a spooled 'running' record must never overwrite the 'done'
        # record a post-recovery direct write already committed
        monkeypatch.setenv("VRPMS_CB_RESET_S", "0.05")
        inner = ScriptedDB(fail_writes=1, fail_reads=2)
        db = resilient(inner)
        db.save_job("j", {"status": "running"})  # spooled (failure #1)
        errors: list = []
        db.get_locations_by_id(1, errors)  # failures #2-3 -> circuit opens
        time.sleep(0.08)
        db2 = resilient(inner)
        db2.save_job("j", {"status": "done"})  # half-open probe: direct write
        deadline = time.monotonic() + 2.0
        while len(db2._res.journal) and time.monotonic() < deadline:
            time.sleep(0.01)
        assert inner.jobs["j"]["record"] == {"status": "done"}

    def test_spooled_solution_insert_returns_sentinel(self):
        inner = ScriptedDB(fail_writes=-1)
        db = resilient(inner)
        # drive _insert_solution directly to check the 200-not-400 deal:
        # a spooled save must hand _save a non-None value
        out = db._insert_solution({"name": "x"})
        assert out == {"name": "x"}
        assert db.degraded is True


class TestFallbackBounds:
    def test_fallback_store_evicts_stalest(self):
        fb = FallbackStore(limit=2)
        fb.put("a", 1)
        fb.put("b", 2)
        fb.get("a")  # refresh a
        fb.put("c", 3)  # evicts b
        assert fb.get("b") == (False, None)
        assert fb.get("a") == (True, 1)

    def test_journal_bounded_drops_oldest(self):
        j = WriteJournal(limit=2)
        j.append("m", (1,))
        j.append("m", (2,))
        j.append("m", (3,))
        assert j.dropped == 1
        assert [e[1][0] for e in j.drain()] == [2, 3]

    def test_journal_discard_and_tombstone(self):
        j = WriteJournal(limit=8)
        j.append("m", (1,), key="k")
        j.discard("k")
        assert len(j) == 0 and j.stale("k")
        j.append("m", (2,), key="k")  # a NEW spool lifts the tombstone
        assert not j.stale("k") and len(j) == 1


# ---------------------------------------------------------------------------
# Worker supervision (watchdog)
# ---------------------------------------------------------------------------

from vrpms_tpu.sched import DONE, FAILED, Job, Scheduler  # noqa: E402


def make_scheduler(runner, **kw):
    kw.setdefault("queue_limit", 16)
    kw.setdefault("window_s", 0.0)
    kw.setdefault("max_batch", 4)
    kw.setdefault("watchdog_s", 0.03)
    kw.setdefault("wedge_grace_s", 0.15)
    return Scheduler(runner, **kw)


class TestSupervision:
    def test_crash_requeues_once_and_completes(self):
        crashes = []
        events = []

        def runner(jobs):
            if jobs[0].payload.get("crash") and not crashes:
                crashes.append(1)
                raise SystemExit("worker dies")  # BaseException: thread death
            for j in jobs:
                j.result = {"run": "ok"}

        s = make_scheduler(runner, on_event=lambda n, j: events.append(n))
        try:
            job = Job(payload={"crash": True})
            s.submit(job)
            assert job.wait(5.0), "requeued job never completed"
            assert job.status == DONE and job.result == {"run": "ok"}
            assert job.requeued is True
            assert s.restarts.get("default") == 1
            assert "requeued" in events
        finally:
            s.shutdown()

    def test_second_crash_fails_cleanly(self):
        def runner(jobs):
            if jobs[0].payload.get("crash"):
                raise SystemExit("worker dies again")
            for j in jobs:
                j.result = {}

        events = []
        s = make_scheduler(runner, on_event=lambda n, j: events.append(n))
        try:
            job = Job(payload={"crash": True})
            s.submit(job)
            assert job.wait(5.0), "poison job left hanging"
            assert job.status == FAILED
            assert job.errors[0]["what"] == "Scheduler crashed"
            assert s.restarts.get("default") == 2
            assert "crashed" in events
        finally:
            s.shutdown()

    def test_queued_jobs_survive_a_crash(self):
        def runner(jobs):
            if jobs[0].payload.get("crash") and not jobs[0].requeued:
                raise SystemExit("boom")
            for j in jobs:
                j.result = {"id": j.id}

        s = make_scheduler(runner)
        try:
            first = Job(payload={"crash": True}, bucket=None)
            behind = [Job(payload={}) for _ in range(2)]
            s.submit(first)
            for j in behind:
                s.submit(j)
            for j in [first] + behind:
                assert j.wait(5.0), "job stranded by the crash"
                assert j.status == DONE
            assert not behind[0].requeued  # only in-flight jobs requeue
        finally:
            s.shutdown()

    def test_wedged_worker_is_superseded(self):
        release = threading.Event()
        calls = []

        def runner(jobs):
            calls.append(len(jobs))
            if len(calls) == 1:
                release.wait(10.0)  # wedge: far past budget + grace
                return
            for j in jobs:
                j.result = {"retry": True}

        s = make_scheduler(runner)
        try:
            job = Job(payload={}, time_limit=0.1)
            s.submit(job)
            assert job.wait(5.0), "wedged job never superseded"
            assert job.status == DONE and job.result == {"retry": True}
            assert job.requeued is True
            assert s.restarts.get("default") == 1
        finally:
            release.set()  # let the abandoned thread exit
            s.shutdown()

    def test_unbounded_jobs_never_wedge_detect(self):
        release = threading.Event()

        def runner(jobs):
            release.wait(0.6)  # longer than grace, but no budget to breach
            for j in jobs:
                j.result = {}

        s = make_scheduler(runner)
        try:
            job = Job(payload={})  # no time limit
            s.submit(job)
            assert job.wait(5.0)
            assert job.status == DONE
            assert not job.requeued
            assert not s.restarts
        finally:
            release.set()
            s.shutdown()

    def test_worker_health_reports_dead_without_watchdog(self):
        def runner(jobs):
            raise SystemExit("die")

        s = Scheduler(runner, watchdog_s=0.0)  # supervision off
        try:
            job = Job(payload={})
            s.submit(job)
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if s.worker_health().get("default") == "dead":
                    break
                time.sleep(0.02)
            assert s.worker_health() == {"default": "dead"}
        finally:
            s.shutdown()
