"""CPU parity for the accelerator (one-hot) GA/ACO fitness paths.

The TPU/GPU default fitness is greedy_split_cost_hot_batch — one-hot leg
selection plus pointer-doubling route boundaries — and the hot ACO
construction scores via one-hot matmuls. CI runs on CPU where 'auto'
resolves to 'gather', so these tests force the hot formulations and pin
them against the scan/gather versions (the same strategy
tests/test_onehot.py uses for the giant-tour paths).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from vrpms_tpu.core import make_instance
from vrpms_tpu.core.cost import CostWeights
from vrpms_tpu.core.split import (
    greedy_split_cost_batch,
    greedy_split_cost_hot_batch,
)
from vrpms_tpu.solvers.aco import _construct_orders
from vrpms_tpu.solvers.common import perm_fitness_fn


def _rand_instance(rng, n, v, q):
    d = rng.uniform(1, 60, size=(n + 1, n + 1))
    np.fill_diagonal(d, 0)
    demands = np.concatenate([[0], rng.integers(1, 9, n)])
    return make_instance(d, demands=demands, capacities=[float(q)] * v)


def _rand_perms(rng, b, n):
    return jnp.asarray(
        np.stack([rng.permutation(np.arange(1, n + 1)) for _ in range(b)]),
        dtype=jnp.int32,
    )


class TestGreedySplitHot:
    @pytest.mark.parametrize("n,v,q", [(6, 2, 9), (19, 3, 14), (33, 5, 21)])
    def test_matches_scan_split(self, rng, n, v, q):
        inst = _rand_instance(rng, n, v, q)
        perms = _rand_perms(rng, 16, n)
        c_ref, r_ref = greedy_split_cost_batch(perms, inst)
        c_hot, r_hot = greedy_split_cost_hot_batch(perms, inst)
        # identical route structure; costs to bf16 rounding of D
        np.testing.assert_array_equal(
            np.asarray(r_ref), np.asarray(r_hot).astype(np.int32)
        )
        np.testing.assert_allclose(np.asarray(c_hot), np.asarray(c_ref), rtol=2e-2)

    def test_oversize_customer_rides_alone(self, rng):
        # a single customer above capacity must still occupy one route,
        # exactly like the scan rule (progress clamp in the jump fn)
        d = np.ones((4, 4)) - np.eye(4)
        inst = make_instance(d, demands=[0, 9, 1, 1], capacities=[5.0, 5.0, 5.0])
        perms = jnp.asarray([[1, 2, 3], [2, 1, 3], [3, 2, 1]], dtype=jnp.int32)
        c_ref, r_ref = greedy_split_cost_batch(perms, inst)
        c_hot, r_hot = greedy_split_cost_hot_batch(perms, inst)
        np.testing.assert_array_equal(
            np.asarray(r_ref), np.asarray(r_hot).astype(np.int32)
        )
        np.testing.assert_allclose(np.asarray(c_hot), np.asarray(c_ref), rtol=1e-6)

    def test_fitness_fn_hot_matches_gather(self, rng):
        inst = _rand_instance(rng, 15, 2, 12)
        w = CostWeights.make()
        perms = _rand_perms(rng, 8, 15)
        ref = np.asarray(perm_fitness_fn(inst, w, mode="gather")(perms))
        hot = np.asarray(perm_fitness_fn(inst, w, mode="onehot")(perms))
        # fleet-overflow penalties are exact; distances bf16-rounded
        np.testing.assert_allclose(hot, ref, rtol=2e-2)


class TestGaOperatorsHot:
    def test_hot_ox_structure(self, rng):
        from vrpms_tpu.solvers.ga import order_crossover_hot

        n, pop = 22, 12
        p1 = _rand_perms(rng, pop, n)
        p2 = _rand_perms(rng, pop, n)
        key = jax.random.key(5)
        children = np.asarray(order_crossover_hot(p1, p2, key))
        ij = np.asarray(jax.random.randint(key, (pop, 2), 0, n))
        for p in range(pop):
            child = children[p]
            assert sorted(child) == list(range(1, n + 1))
            i, j = min(ij[p]), max(ij[p])
            # OX contract: p1's cut segment kept in place...
            assert np.array_equal(child[i : j + 1], np.asarray(p1)[p, i : j + 1])
            # ...and the rest follows p2's relative order
            seg = set(child[i : j + 1].tolist())
            rest = [v for v in child if v not in seg]
            assert rest == [v for v in np.asarray(p2)[p] if v not in seg]

    def test_hot_generation_evolves_and_stays_valid(self, rng):
        from vrpms_tpu.solvers.ga import GAParams, ga_generation

        inst = _rand_instance(rng, 14, 3, 12)
        w = CostWeights.make()
        fitness = perm_fitness_fn(inst, w, mode="onehot")
        perms = _rand_perms(rng, 32, 14)
        fits = fitness(perms)
        best0 = float(jnp.min(fits))
        params = GAParams(population=32, elites=4)
        for gen in range(5):
            prev_best = float(jnp.min(fits))
            perms, fits = ga_generation(
                perms, fits, jax.random.key(9), gen, fitness, params, "onehot"
            )
            # elitism carries the best individuals forward, so the
            # population minimum can never regress between generations
            assert float(jnp.min(fits)) <= prev_best + 1e-3
        for row in np.asarray(perms):
            assert sorted(row) == list(range(1, 15))
        assert float(jnp.min(fits)) <= best0 + 1e-3


class TestAcoConstructionHot:
    def test_orders_are_permutations_and_biased(self, rng):
        n_nodes = 12
        d = rng.uniform(1, 50, size=(n_nodes, n_nodes))
        tau = jnp.ones((n_nodes, n_nodes))
        eta = jnp.asarray(1.0 / np.maximum(d, 1e-6)) ** 2.5
        for mode in ("gather", "onehot"):
            orders = _construct_orders(jax.random.key(0), tau, eta, 16, mode=mode)
            assert orders.shape == (16, n_nodes - 1)
            for row in np.asarray(orders):
                assert sorted(row) == list(range(1, n_nodes))

    def test_hot_and_gather_sample_same_distribution(self, rng):
        # identical keys and uniform pheromone: choices differ only via
        # bf16 log-score rounding, so the aggregate next-hop frequency
        # from the depot must match closely across modes
        n_nodes = 8
        d = rng.uniform(1, 50, size=(n_nodes, n_nodes))
        tau = jnp.ones((n_nodes, n_nodes))
        eta = jnp.asarray(1.0 / np.maximum(d, 1e-6)) ** 2.5
        a = np.asarray(
            _construct_orders(jax.random.key(1), tau, eta, 512, mode="gather")
        )
        b = np.asarray(
            _construct_orders(jax.random.key(1), tau, eta, 512, mode="onehot")
        )
        freq_a = np.bincount(a[:, 0], minlength=n_nodes) / 512
        freq_b = np.bincount(b[:, 0], minlength=n_nodes) / 512
        assert np.abs(freq_a - freq_b).max() < 0.1
