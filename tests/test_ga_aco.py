"""GA and ACO golden tests vs the BF oracle, plus operator unit tests."""

import numpy as np
import jax
import jax.numpy as jnp

from vrpms_tpu.core.encoding import is_valid_giant
from vrpms_tpu.solvers import solve_vrp_bf, solve_tsp_bf
from vrpms_tpu.solvers.ga import GAParams, order_crossover, mutate, solve_ga
from vrpms_tpu.solvers.aco import ACOParams, solve_aco, _construct_orders
from tests.test_sa import euclidean_cvrp
from tests.test_core_cost import random_instance


def _is_perm(x, n):
    return sorted(np.asarray(x).tolist()) == list(range(1, n + 1))


class TestOperators:
    def test_order_crossover_is_permutation(self):
        n = 12
        rng = np.random.default_rng(1)
        for seed in range(20):
            p1 = jnp.asarray(rng.permutation(np.arange(1, n + 1)), dtype=jnp.int32)
            p2 = jnp.asarray(rng.permutation(np.arange(1, n + 1)), dtype=jnp.int32)
            child = order_crossover(p1, p2, jax.random.key(seed))
            assert _is_perm(child, n)

    def test_crossover_inherits_segment(self):
        # With identical parents the child must equal them.
        p = jnp.arange(1, 11, dtype=jnp.int32)
        child = order_crossover(p, p, jax.random.key(0))
        assert child.tolist() == p.tolist()

    def test_mutate_is_permutation(self):
        n = 10
        p = jnp.arange(1, n + 1, dtype=jnp.int32)
        for seed in range(20):
            m = mutate(p, jax.random.key(seed), rate=1.0)
            assert _is_perm(m, n)

    def test_construct_orders_are_permutations(self):
        n_nodes = 9
        tau = jnp.ones((n_nodes, n_nodes))
        eta = jnp.ones((n_nodes, n_nodes))
        orders = _construct_orders(jax.random.key(0), tau, eta, 16)
        assert orders.shape == (16, n_nodes - 1)
        for a in range(16):
            assert _is_perm(orders[a], n_nodes - 1)


class TestGA:
    def test_near_optimal_cvrp(self, rng):
        inst = euclidean_cvrp(rng, n=8, v=3, q=8)
        opt = float(solve_vrp_bf(inst).cost)
        res = solve_ga(inst, key=0, params=GAParams(population=128, generations=300))
        assert is_valid_giant(res.giant, 7, 3)
        assert float(res.cost) <= opt * 1.05 + 1e-3
        assert float(res.breakdown.cap_excess) == 0.0

    def test_respects_population_and_generations(self, rng):
        inst = euclidean_cvrp(rng, n=10, v=2, q=20)
        res = solve_ga(inst, key=1, params=GAParams(population=32, generations=50))
        # pop + default immigrants (8, clamped) genomes evaluated per gen
        assert int(res.evals) == (32 + 8) * 50
        res0 = solve_ga(
            inst, key=1,
            params=GAParams(population=32, generations=50, immigrants=0),
        )
        assert int(res0.evals) == 32 * 50

    def test_tw_instance(self, rng):
        inst = random_instance(rng, n=8, v=2, tw=True)
        res = solve_ga(inst, key=2, params=GAParams(population=64, generations=100))
        assert is_valid_giant(res.giant, 7, 2)

    def test_immigrant_generation_valid_in_both_modes(self, rng):
        # the default-on immigrant path: every child (immigrants
        # included) must stay a valid permutation, and the elite-carried
        # best must never regress across a generation
        from vrpms_tpu.solvers.common import perm_fitness_fn
        from vrpms_tpu.solvers.ga import ga_generation, initial_perms
        from vrpms_tpu.core.cost import CostWeights

        inst = euclidean_cvrp(rng, n=12, v=3, q=10)
        w = CostWeights.make()
        p = GAParams(population=24, elites=4, immigrants=6)
        for mode in ("gather", "onehot"):
            fitness = perm_fitness_fn(inst, w, p.fleet_penalty, mode=mode)
            perms = initial_perms(jax.random.key(0), 24, inst, p, mode)
            fits = fitness(perms)
            best0 = float(jnp.min(fits))
            for gen in range(3):
                perms, fits = ga_generation(
                    perms, fits, jax.random.key(1), gen, fitness, p, mode,
                    d=inst.durations[0],
                )
            for row in np.asarray(perms):
                assert sorted(row) == list(range(1, 12)), mode
            assert float(jnp.min(fits)) <= best0 + 1e-3, mode

    def test_pool_returns_champion_first(self, rng):
        inst = euclidean_cvrp(rng, n=10, v=2, q=20)
        res = solve_ga(
            inst, key=5, params=GAParams(population=32, generations=40), pool=5
        )
        assert res.pool is not None and res.pool.shape[0] == 5
        assert np.array_equal(np.asarray(res.pool[0]), np.asarray(res.giant))
        for g in np.asarray(res.pool):
            assert is_valid_giant(g, 9, 2)

    def test_deadline_truncates_but_returns_valid_best(self, rng):
        inst = euclidean_cvrp(rng, n=10, v=2, q=20)
        res = solve_ga(
            inst,
            key=3,
            params=GAParams(population=32, generations=100_000),
            deadline_s=1e-6,
        )
        assert is_valid_giant(res.giant, 9, 2)
        assert 32 * 1 <= int(res.evals) < 32 * 100_000  # truncated early

    def test_deadline_full_budget_matches_unbounded(self, rng):
        inst = euclidean_cvrp(rng, n=10, v=2, q=20)
        p = GAParams(population=32, generations=60)
        free = solve_ga(inst, key=4, params=p)
        timed = solve_ga(inst, key=4, params=p, deadline_s=3600.0)
        # deadline never hit: block-composed run matches the single block
        assert float(free.cost) == float(timed.cost)
        assert np.array_equal(np.asarray(free.giant), np.asarray(timed.giant))


class TestACO:
    def test_near_optimal_tsp(self, rng):
        n = 8
        pts = rng.uniform(0, 100, size=(n, 2))
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        from vrpms_tpu.core import make_instance

        inst = make_instance(d, n_vehicles=1)
        opt = float(solve_tsp_bf(inst).cost)
        res = solve_aco(inst, key=0, params=ACOParams(n_ants=64, n_iters=150))
        assert is_valid_giant(res.giant, n - 1, 1)
        assert float(res.cost) <= opt * 1.05 + 1e-3

    def test_near_optimal_cvrp(self, rng):
        inst = euclidean_cvrp(rng, n=8, v=3, q=8)
        opt = float(solve_vrp_bf(inst).cost)
        res = solve_aco(inst, key=1, params=ACOParams(n_ants=64, n_iters=150))
        assert float(res.cost) <= opt * 1.10 + 1e-3
        assert float(res.breakdown.cap_excess) == 0.0

    def test_candidate_list_competitive_with_full_sampling(self, rng):
        """KNN-restricted construction (default) must not lose to full
        sampling at equal budget (at n=100 on TPU it wins outright:
        19041 vs 19274 at 128x300 — BASELINE.md)."""
        inst = euclidean_cvrp(rng, n=24, v=4, q=10)
        budget = dict(n_ants=32, n_iters=80)
        knn = solve_aco(inst, key=4, params=ACOParams(**budget, knn_k=8))
        full = solve_aco(inst, key=4, params=ACOParams(**budget, knn_k=0))
        assert is_valid_giant(knn.giant, 23, 4)
        assert float(knn.cost) <= float(full.cost) * 1.10

    def test_deadline_truncates_but_returns_valid_best(self, rng):
        inst = euclidean_cvrp(rng, n=8, v=2, q=12)
        res = solve_aco(
            inst,
            key=2,
            params=ACOParams(n_ants=16, n_iters=100_000),
            deadline_s=1e-6,
        )
        assert is_valid_giant(res.giant, 7, 2)
        assert 16 * 1 <= int(res.evals) < 16 * 100_000  # truncated early

    def test_deadline_full_budget_matches_unbounded(self, rng):
        inst = euclidean_cvrp(rng, n=8, v=2, q=12)
        p = ACOParams(n_ants=16, n_iters=40)
        free = solve_aco(inst, key=3, params=p)
        timed = solve_aco(inst, key=3, params=p, deadline_s=3600.0)
        assert float(free.cost) == float(timed.cost)
        assert np.array_equal(np.asarray(free.giant), np.asarray(timed.giant))

    def test_onehot_deposit_matches_scatter(self, rng):
        # the MXU outer-product deposit must add exactly the scatter's
        # multiset of edges, repeated (0,0) hops of unused vehicles
        # included
        from vrpms_tpu.solvers.aco import deposit

        n = 9
        tau = jnp.asarray(rng.uniform(0.1, 1.0, size=(n, n)), jnp.float32)
        # giant with trailing empty routes -> repeated (0, 0) edges
        giant = jnp.asarray([0, 3, 1, 0, 5, 2, 4, 0, 6, 7, 8, 0, 0, 0], jnp.int32)
        amount = jnp.float32(0.37)
        got = deposit(tau, giant, amount, hot=True)
        want = deposit(tau, giant, amount, hot=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)

    def test_warm_start_never_worse_than_seed(self, rng):
        from vrpms_tpu.core.cost import CostWeights, exact_cost
        from vrpms_tpu.core.split import greedy_split_giant
        from vrpms_tpu.solvers.local_search import nearest_neighbor_perm

        inst = euclidean_cvrp(rng, n=10, v=3, q=8)
        w = CostWeights.make()
        # a deliberately good seed: the NN-constructed order
        seed_perm = nearest_neighbor_perm(inst)
        seed_cost = float(exact_cost(greedy_split_giant(seed_perm, inst), inst, w)[1])
        res = solve_aco(
            inst, key=5, params=ACOParams(n_ants=8, n_iters=3),
            init_perm=seed_perm,
        )
        # 3 iterations of a tiny colony rarely improve on NN; the warm
        # incumbent guarantees the solve never returns worse either way
        assert float(res.cost) <= seed_cost + 1e-3

    def test_elite_pool_sorted_valid(self, rng):
        inst = euclidean_cvrp(rng, n=10, v=3, q=8)
        res = solve_aco(
            inst, key=6, params=ACOParams(n_ants=16, n_iters=30), pool=4
        )
        assert res.pool is not None and res.pool.shape[0] == 4
        from vrpms_tpu.core.cost import CostWeights, exact_cost

        w = CostWeights.make()
        costs = [float(exact_cost(g, inst, w)[1]) for g in res.pool]
        for g in res.pool:
            assert is_valid_giant(np.asarray(g), 9, 3)
        # the pool is exact-re-ranked at the solver boundary: best
        # first, and the champion never exact-prices worse than pool[0]
        assert costs[0] == min(costs)
        assert float(res.cost) <= costs[0] + 1e-3


class TestACOIslands:
    def test_islands_solve_valid_and_competitive(self, rng):
        from vrpms_tpu.mesh import IslandParams, make_mesh, solve_aco_islands

        inst = euclidean_cvrp(rng, n=10, v=3, q=8)
        mesh = make_mesh(n_devices=4)
        res = solve_aco_islands(
            inst,
            key=0,
            mesh=mesh,
            params=ACOParams(n_ants=16, n_iters=40),
            island_params=IslandParams(migrate_every=10, n_migrants=1),
            pool=4,
        )
        assert is_valid_giant(np.asarray(res.giant), 9, 3)
        assert res.pool is not None and res.pool.shape[0] == 4
        # islands at 4x the colony count must not lose badly to one colony
        single = solve_aco(inst, key=0, params=ACOParams(n_ants=16, n_iters=40))
        assert float(res.cost) <= float(single.cost) * 1.10 + 1e-3

    def test_islands_deadline_truncates(self, rng):
        from vrpms_tpu.mesh import IslandParams, make_mesh, solve_aco_islands

        inst = euclidean_cvrp(rng, n=8, v=2, q=12)
        mesh = make_mesh(n_devices=2)
        res = solve_aco_islands(
            inst,
            key=1,
            mesh=mesh,
            params=ACOParams(n_ants=8, n_iters=100_000),
            island_params=IslandParams(migrate_every=10, n_migrants=1),
            deadline_s=1e-6,
        )
        assert is_valid_giant(np.asarray(res.giant), 7, 2)
        assert int(res.evals) < 2 * 8 * 100_000


class TestGaInit:
    def test_nn_population_not_worse_than_random(self):
        import numpy as np
        from vrpms_tpu.io.synth import synth_cvrp
        from vrpms_tpu.solvers import GAParams, solve_ga

        inst = synth_cvrp(26, 4, seed=5)
        budget = dict(population=64, generations=40)
        nn = solve_ga(inst, key=2, params=GAParams(**budget))
        rnd = solve_ga(inst, key=2, params=GAParams(**budget, init="random"))
        assert float(nn.cost) <= float(rnd.cost) * 1.02

    def test_initial_perms_valid_and_validated(self):
        import numpy as np
        import jax
        import pytest
        from vrpms_tpu.io.synth import synth_cvrp
        from vrpms_tpu.solvers.ga import GAParams, initial_perms

        inst = synth_cvrp(13, 2, seed=1)
        for init in ("nn", "random"):
            perms = initial_perms(
                jax.random.key(0), 8, inst, GAParams(init=init), "gather"
            )
            assert perms.shape == (8, 12)
            for row in np.asarray(perms):
                assert sorted(row) == list(range(1, 13))
        with pytest.raises(ValueError):
            initial_perms(
                jax.random.key(0), 8, inst, GAParams(init="x"), "gather"
            )
