"""Concurrent-service hardening (VERDICT round-1 #8).

service/app.py serves with ThreadingHTTPServer: concurrent requests run
the jit builders (lru_cache), the store, and the single JAX device
queue from multiple threads at once. These tests fire parallel POSTs
with MIXED instance shapes and algorithms and assert:

  * every response carries a correct contract envelope;
  * results are bitwise IDENTICAL to the same bodies solved serially
    (seeded solves are deterministic, so any cross-request state bleed
    — shared buffers, wrong instance, swapped params — shows up as a
    changed result);
  * bad requests interleaved with solves still get their 400s.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import store.memory as mem
from service.app import serve


@pytest.fixture(scope="module")
def server():
    import os

    os.environ["VRPMS_STORE"] = "memory"
    srv = serve(port=0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()


@pytest.fixture(autouse=True)
def seeded():
    mem.reset()
    rng = np.random.default_rng(7)
    for key, n in (("small", 6), ("big", 11)):
        pts = rng.uniform(0, 100, size=(n, 2))
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        mem.seed_locations(
            key, [{"id": i, "demand": 2 if i else 0} for i in range(n)]
        )
        mem.seed_durations(key, d.tolist())
    yield


@pytest.fixture
def no_cache():
    """Disable the solution cache: the serial ground-truth round would
    otherwise warm it and turn the concurrent round into exact hits —
    correct, but no longer exercising concurrent SOLVES."""
    import os

    saved = os.environ.get("VRPMS_CACHE")
    os.environ["VRPMS_CACHE"] = "off"
    yield
    if saved is None:
        os.environ.pop("VRPMS_CACHE", None)
    else:
        os.environ["VRPMS_CACHE"] = saved


def post(base, path, body):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=600) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def vrp_body(key, n, **over):
    body = {
        "solutionName": f"con-{key}",
        "solutionDescription": "t",
        "locationsKey": key,
        "durationsKey": key,
        "capacities": [2 * n, 2 * n, 2 * n],
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": 3,
        "iterationCount": 300,
        "populationSize": 16,
    }
    body.update(over)
    return body


def tsp_body(key, n, **over):
    body = {
        "solutionName": f"con-t-{key}",
        "solutionDescription": "t",
        "locationsKey": key,
        "durationsKey": key,
        "customers": list(range(1, n)),
        "startNode": 0,
        "startTime": 0,
        "seed": 3,
        "iterationCount": 300,
        "populationSize": 16,
    }
    body.update(over)
    return body


REQUESTS = [
    ("/api/vrp/sa", vrp_body("small", 6)),
    ("/api/vrp/sa", vrp_body("big", 11)),
    ("/api/vrp/ga", vrp_body("small", 6, multiThreaded=True,
                             randomPermutationCount=16, iterationCount=40)),
    ("/api/tsp/sa", tsp_body("big", 11)),
    ("/api/vrp/aco", vrp_body("big", 11, iterationCount=40)),
    ("/api/vrp/sa", vrp_body("small", 6, localSearch=True,
                             includeStats=True)),
    ("/api/vrp/sa", {"capacities": [1]}),  # 400: missing params
    ("/api/tsp/bf", tsp_body("small", 6)),
]


class TestConcurrentRequests:
    def test_parallel_posts_match_serial_results(self, server, no_cache):
        # serial ground truth first (also pre-compiles every shape, so
        # the concurrent round exercises dispatch, not compile races)
        serial = [post(server, path, body) for path, body in REQUESTS]

        results = [None] * len(REQUESTS)

        def hit(i):
            path, body = REQUESTS[i]
            results[i] = post(server, path, body)

        threads = [
            threading.Thread(target=hit, args=(i,))
            for i in range(len(REQUESTS))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
            assert not t.is_alive(), "request thread hung"

        for i, ((s_status, s_resp), (c_status, c_resp)) in enumerate(
            zip(serial, results)
        ):
            assert c_status == s_status, (i, c_resp)
            if s_status == 200:
                # strip stats (wallMs differs run to run) then demand
                # bitwise-identical results — seeded solves are
                # deterministic, so any difference means state bled
                # between concurrent requests
                s_msg = dict(s_resp["message"])
                c_msg = dict(c_resp["message"])
                s_msg.pop("stats", None)
                c_msg.pop("stats", None)
                assert c_msg == s_msg, f"request {i} diverged under concurrency"
            else:
                assert c_resp["success"] is False
                assert c_resp["errors"] == s_resp["errors"]

    def test_concurrent_first_compiles_distinct_shapes(self, server):
        # no serial warmup here: two DIFFERENT shapes race their first
        # jit compile in parallel threads (the lru_cache + trace path)
        bodies = [
            ("/api/vrp/sa", vrp_body("small", 6, iterationCount=123)),
            ("/api/vrp/sa", vrp_body("big", 11, iterationCount=456)),
        ]
        results = [None, None]

        def hit(i):
            path, body = bodies[i]
            results[i] = post(server, path, body)

        threads = [threading.Thread(target=hit, args=(i,)) for i in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
            assert not t.is_alive(), "request thread hung"
        for i, (status, resp) in enumerate(results):
            assert status == 200, (i, resp)
            n = 6 if i == 0 else 11
            visited = sorted(
                c
                for v_ in resp["message"]["vehicles"]
                for c in v_["tour"][1:-1]
            )
            assert visited == list(range(1, n))
