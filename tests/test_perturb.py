"""Ruin-and-recreate perturbation: validity, guarantee, and usefulness."""

import numpy as np
import jax
import jax.numpy as jnp

from vrpms_tpu.core.cost import CostWeights, exact_cost_batch
from vrpms_tpu.core.encoding import is_valid_giant
from vrpms_tpu.core.split import greedy_split_giant
from vrpms_tpu.io.synth import synth_cvrp
from vrpms_tpu.solvers.local_search import nearest_neighbor_perm
from vrpms_tpu.solvers.perturb import _perm_of_giant, ruin_recreate_clones


def incumbent(inst):
    return greedy_split_giant(nearest_neighbor_perm(inst), inst)


class TestRuinRecreate:
    def test_outputs_valid_and_chain0_exact(self):
        inst = synth_cvrp(40, 6, seed=3)
        g = incumbent(inst)
        clones = ruin_recreate_clones(jax.random.key(1), 16, g, inst)
        assert clones.shape == (16, g.shape[0])
        assert np.array_equal(np.asarray(clones[0]), np.asarray(g))
        for row in np.asarray(clones):
            assert is_valid_giant(row, inst.n_customers, inst.n_vehicles)

    def test_perm_of_giant_roundtrip(self):
        inst = synth_cvrp(13, 3, seed=5)
        g = incumbent(inst)
        perm = _perm_of_giant(g, inst.n_customers)
        # same customers, same visiting order as the giant
        walked = [int(c) for c in np.asarray(g) if c != 0]
        assert [int(c) for c in np.asarray(perm)] == walked

    def test_clones_stay_competitive(self):
        # greedy cheapest-gap reinsertion must produce starts in the
        # incumbent's quality neighborhood, not random-shuffle quality
        inst = synth_cvrp(60, 8, seed=9)
        w = CostWeights.make()
        g = incumbent(inst)
        base = float(exact_cost_batch(g[None], inst, w)[0])
        clones = ruin_recreate_clones(jax.random.key(2), 32, g, inst)
        costs = np.asarray(exact_cost_batch(clones, inst, w))
        assert float(np.median(costs)) <= base * 1.25
        # and a solid majority genuinely differ from the incumbent
        # (some ruins legitimately reinsert into the identical order)
        distinct = sum(
            not np.array_equal(np.asarray(row), np.asarray(g))
            for row in clones[1:]
        )
        assert distinct >= 16

    def test_ils_reseed_ruin_mode_runs(self):
        from vrpms_tpu.solvers.ils import ILSParams, solve_ils
        from vrpms_tpu.solvers.sa import SAParams

        inst = synth_cvrp(20, 4, seed=2)
        res = solve_ils(
            inst,
            key=0,
            params=ILSParams.from_budget(
                2, SAParams(n_chains=16, n_iters=0), 200, pool=4,
                reseed="ruin",
            ),
        )
        assert is_valid_giant(
            np.asarray(res.giant), inst.n_customers, inst.n_vehicles
        )
