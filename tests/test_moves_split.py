"""Tests for neighborhood moves and permutation splitting."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from vrpms_tpu.core.encoding import is_valid_giant, random_giant
from vrpms_tpu.core.split import (
    greedy_split_cost,
    greedy_split_giant,
    optimal_split_cost,
    optimal_split_routes,
)
from vrpms_tpu.core.cost import evaluate_giant
from vrpms_tpu.moves import (
    reverse_segment,
    rotate_segment,
    swap_positions,
    random_move,
)
from tests.oracle import naive_greedy_split, route_list_cost
from tests.test_core_cost import random_instance


class TestMoves:
    def setup_method(self):
        self.g = jnp.asarray([0, 3, 1, 0, 4, 2, 5, 0], dtype=jnp.int32)

    def test_reverse(self):
        out = reverse_segment(self.g, 2, 5)
        assert out.tolist() == [0, 3, 2, 4, 0, 1, 5, 0]

    def test_reverse_identity(self):
        assert reverse_segment(self.g, 4, 4).tolist() == self.g.tolist()

    def test_rotate(self):
        # left-rotate [1,0,4,2] by 1 -> [0,4,2,1]
        out = rotate_segment(self.g, 2, 5, 1)
        assert out.tolist() == [0, 3, 0, 4, 2, 1, 5, 0]

    def test_swap(self):
        out = swap_positions(self.g, 1, 6)
        assert out.tolist() == [0, 5, 1, 0, 4, 2, 3, 0]

    def test_random_move_preserves_validity(self):
        g = random_giant(jax.random.key(0), 12, 4)
        for seed in range(50):
            g = random_move(jax.random.key(seed), g)
        assert is_valid_giant(g, 12, 4)

    def test_random_move_pins_endpoints(self):
        g = random_giant(jax.random.key(1), 12, 4)
        moved = jax.vmap(random_move, in_axes=(0, None))(
            jax.random.split(jax.random.key(2), 64), g
        )
        assert bool((moved[:, 0] == 0).all())
        assert bool((moved[:, -1] == 0).all())


class TestSplit:
    def test_greedy_matches_oracle(self, rng):
        for trial in range(10):
            n = int(rng.integers(4, 12))
            inst = random_instance(rng, n=n, v=3)
            perm = jnp.asarray(
                rng.permutation(np.arange(1, n)), dtype=jnp.int32
            )
            cost, n_routes = greedy_split_cost(perm, inst)
            want_cost, want_routes = naive_greedy_split(perm, inst)
            np.testing.assert_allclose(float(cost), want_cost, rtol=1e-5)
            assert int(n_routes) == want_routes

    def test_greedy_giant_consistent(self, rng):
        for trial in range(10):
            n = int(rng.integers(4, 12))
            inst = random_instance(rng, n=n, v=4)
            perm = jnp.asarray(rng.permutation(np.arange(1, n)), dtype=jnp.int32)
            giant = greedy_split_giant(perm, inst)
            assert is_valid_giant(giant, n - 1, 4)
            cost, n_routes = greedy_split_cost(perm, inst)
            if int(n_routes) <= 4:
                c = evaluate_giant(giant, inst)
                np.testing.assert_allclose(
                    float(c.distance), float(cost), rtol=1e-5
                )

    def test_optimal_not_worse_than_greedy(self, rng):
        for trial in range(10):
            n = int(rng.integers(4, 12))
            inst = random_instance(rng, n=n, v=4)
            perm = jnp.asarray(rng.permutation(np.arange(1, n)), dtype=jnp.int32)
            greedy, n_routes = greedy_split_cost(perm, inst)
            opt = optimal_split_cost(perm, inst)
            if int(n_routes) <= 4:
                assert float(opt) <= float(greedy) + 1e-4

    def test_optimal_matches_enumeration(self, rng):
        # Exhaustively enumerate split-point subsets AND order-preserving
        # assignments of the resulting routes to vehicles (random_instance
        # fleets are heterogeneous; a route is only feasible on a vehicle
        # whose own capacity covers it, and vehicles may sit empty).
        import itertools

        for trial in range(5):
            n = 7
            v = 3
            inst = random_instance(rng, n=n, v=v)
            perm = list(rng.permutation(np.arange(1, n)))
            caps = np.asarray(inst.capacities, dtype=float)
            demands = np.asarray(inst.demands)
            best = np.inf
            for n_cuts in range(0, v):  # up to v routes
                for cuts in itertools.combinations(range(1, n - 1), n_cuts):
                    bounds = [0, *cuts, n - 1]
                    routes = [
                        perm[a:b] for a, b in zip(bounds[:-1], bounds[1:])
                    ]
                    loads = [sum(demands[c] for c in r) for r in routes]
                    for slots in itertools.combinations(range(v), len(routes)):
                        if any(
                            load > caps[s] for load, s in zip(loads, slots)
                        ):
                            continue
                        best = min(best, route_list_cost(routes, inst))
                        break  # any feasible assignment prices the same
            got = float(
                optimal_split_cost(jnp.asarray(perm, dtype=jnp.int32), inst)
            )
            if np.isfinite(best):
                np.testing.assert_allclose(got, best, rtol=1e-5)

    def test_reconstruction_matches_cost(self, rng):
        for trial in range(10):
            n = int(rng.integers(5, 12))
            inst = random_instance(rng, n=n, v=4)
            perm = jnp.asarray(rng.permutation(np.arange(1, n)), dtype=jnp.int32)
            opt = float(optimal_split_cost(perm, inst))
            if opt >= 1e8:  # infeasible (some customer over capacity)
                continue
            routes = optimal_split_routes(perm, inst)
            assert sorted(c for r in routes for c in r) == sorted(
                int(c) for c in perm
            )
            np.testing.assert_allclose(
                route_list_cost(routes, inst), opt, rtol=1e-5
            )
