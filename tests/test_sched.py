"""Unit tests for the scheduler subsystem (vrpms_tpu.sched).

Queue admission/backpressure, bucket-aware gathering, worker lifecycle
(deadline-spent expiry, drain-on-shutdown), the service-side bucket key,
and the batched-launch split/merge against solo solves — all under
JAX_PLATFORMS=cpu, no HTTP involved (tests/test_jobs.py covers the
end-to-end surface).
"""

import threading
import time

import numpy as np
import pytest

from vrpms_tpu.sched import (
    FAILED,
    Job,
    JobQueue,
    QueueFull,
    Scheduler,
    expired,
    gather_batch,
)


def make_job(bucket=None, time_limit=None, payload=None):
    return Job(payload=payload or {}, bucket=bucket, time_limit=time_limit)


class TestJobQueue:
    def test_fifo_and_bounded(self):
        q = JobQueue(limit=2)
        a, b = make_job(), make_job()
        q.push(a)
        q.push(b)
        with pytest.raises(QueueFull) as e:
            q.push(make_job())
        assert e.value.retry_after_s >= 1.0
        assert q.pop(0.1) is a
        assert q.pop(0.1) is b
        assert q.pop(0.01) is None

    def test_take_matching_extracts_bucket_preserving_order(self):
        q = JobQueue(limit=10)
        jobs = [
            make_job(bucket="x"),
            make_job(bucket="y"),
            make_job(bucket="x"),
            make_job(bucket="z"),
        ]
        for j in jobs:
            q.push(j)
        taken = q.take_matching("x", max_n=8)
        assert taken == [jobs[0], jobs[2]]
        # the skipped jobs keep FIFO order
        assert q.pop(0.1) is jobs[1]
        assert q.pop(0.1) is jobs[3]
        # None never matches
        q.push(make_job(bucket=None))
        assert q.take_matching(None, max_n=8) == []

    def test_drain_closes_admission(self):
        q = JobQueue(limit=4)
        q.push(make_job())
        drained = q.drain()
        assert len(drained) == 1
        with pytest.raises(QueueFull):
            q.push(make_job())
        assert q.pop(0.01) is None


class TestGather:
    def test_gathers_same_bucket_within_window(self):
        q = JobQueue(limit=10)
        first = make_job(bucket="a")
        matching = [make_job(bucket="a") for _ in range(2)]
        other = make_job(bucket="b")
        for j in matching + [other]:
            q.push(j)
        batch = gather_batch(q, first, window_s=0.05, max_batch=8)
        assert batch == [first] + matching
        assert q.pop(0.1) is other

    def test_solo_bucket_none_returns_immediately(self):
        q = JobQueue(limit=10)
        q.push(make_job(bucket="a"))
        t0 = time.monotonic()
        batch = gather_batch(q, make_job(bucket=None), window_s=0.5, max_batch=8)
        assert len(batch) == 1
        assert time.monotonic() - t0 < 0.2  # no gather wait paid

    def test_max_batch_caps_gather(self):
        q = JobQueue(limit=10)
        first = make_job(bucket="a")
        for _ in range(5):
            q.push(make_job(bucket="a"))
        batch = gather_batch(q, first, window_s=0.05, max_batch=3)
        assert len(batch) == 3
        assert len(q) == 3


class TestExpiry:
    def test_only_positive_limits_expire(self):
        never = make_job(time_limit=None)
        stop_asap = make_job(time_limit=0)
        tight = make_job(time_limit=0.001)
        time.sleep(0.01)
        assert not expired(never)
        assert not expired(stop_asap)  # explicit 0 keeps stop-ASAP meaning
        assert expired(tight)


class TestScheduler:
    def test_merges_same_bucket_and_completes(self):
        seen_batches = []
        release = threading.Event()

        def runner(jobs):
            if jobs[0].payload.get("block"):
                release.wait(5.0)
            seen_batches.append(list(jobs))
            for j in jobs:
                j.result = {"ok": j.id}

        s = Scheduler(runner, queue_limit=16, window_s=0.02, max_batch=8)
        try:
            blocker = Job(payload={"block": True}, bucket=None)
            s.submit(blocker)
            batch_jobs = [make_job(bucket="same") for _ in range(3)]
            for j in batch_jobs:
                s.submit(j)
            release.set()
            for j in [blocker] + batch_jobs:
                assert j.wait(10.0), "job did not complete"
                assert j.status == "done"
                assert j.result == {"ok": j.id}
            # the three same-bucket jobs ran as ONE batch
            assert [len(b) for b in seen_batches] == [1, 3]
            assert batch_jobs[0].batch_size == 3
        finally:
            s.shutdown()

    def test_deadline_spent_in_queue_fails_before_running(self):
        ran = []
        release = threading.Event()

        def runner(jobs):
            if jobs[0].payload.get("block"):
                release.wait(5.0)
            ran.extend(j.id for j in jobs)
            for j in jobs:
                j.result = {}

        s = Scheduler(runner, queue_limit=16, window_s=0.0, max_batch=1)
        try:
            s.submit(Job(payload={"block": True}))
            doomed = make_job(time_limit=0.05)
            unbounded = make_job(time_limit=None)
            s.submit(doomed)
            s.submit(unbounded)
            time.sleep(0.2)  # let the doomed job's budget drain in queue
            release.set()
            assert doomed.wait(10.0) and unbounded.wait(10.0)
            assert doomed.status == FAILED
            assert doomed.id not in ran  # never started
            assert "Deadline exceeded" in doomed.errors[0]["what"]
            assert doomed.queue_wait_s >= 0.05
            assert unbounded.status == "done"
        finally:
            s.shutdown()

    def test_runner_exception_fails_batch_cleanly(self):
        def runner(jobs):
            raise RuntimeError("kaboom")

        s = Scheduler(runner, queue_limit=4, window_s=0.0, max_batch=1)
        try:
            job = make_job()
            s.submit(job)
            assert job.wait(10.0)
            assert job.status == FAILED
            assert "kaboom" in job.errors[0]["reason"]
        finally:
            s.shutdown()

    def test_shutdown_drains_queued_jobs(self):
        release = threading.Event()

        def runner(jobs):
            release.wait(5.0)
            for j in jobs:
                j.result = {}

        s = Scheduler(runner, queue_limit=16, window_s=0.0, max_batch=1)
        s.submit(Job(payload={}))  # occupies the worker
        queued = [make_job() for _ in range(3)]
        for j in queued:
            s.submit(j)
        time.sleep(0.05)
        release.set()
        drained = s.shutdown()
        assert drained >= 1
        for j in queued:
            assert j.wait(1.0), "drained job left hanging"
        assert all(
            j.status in (FAILED, "done") for j in queued
        )
        drained_jobs = [j for j in queued if j.status == FAILED]
        assert drained_jobs, "no queued job was drained"
        assert all(
            "shutting down" in j.errors[0]["reason"] for j in drained_jobs
        )
        # admission is closed after shutdown
        with pytest.raises(QueueFull):
            s.submit(make_job())


def _prep(algorithm="sa", n=7, seed=0, **opts):
    """A service Prepared via the real prepare path (bucket-key tests)."""
    from service.parameters import parse_solver_options
    from service.solve import prepare_vrp

    rng = np.random.default_rng(seed)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    locations = [{"id": i, "demand": 2 if i else 0} for i in range(n)]
    params = {
        "name": "t", "auth": None, "description": "",
        "capacities": [20, 20], "start_times": [0, 0],
        "ignored_customers": [], "completed_customers": [],
    }
    errors: list = []
    parsed = parse_solver_options(dict(opts), errors)
    assert not errors
    prep = prepare_vrp(
        algorithm, params, parsed, {}, locations, d.tolist(), errors, None
    )
    assert prep is not None and not errors
    return prep


class TestBucketKey:
    def test_same_shape_same_key(self):
        from service.jobs import _bucket_key

        k1 = _bucket_key(_prep(seed=1))
        k2 = _bucket_key(_prep(seed=2))
        assert k1 is not None and k1 == k2

    def test_shape_algorithm_and_options_split_buckets(self):
        from service.jobs import _bucket_key

        base = _bucket_key(_prep())
        assert _bucket_key(_prep(n=9)) != base
        assert _bucket_key(_prep(algorithm="ga")) is None
        assert _bucket_key(_prep(iterationCount=99)) != base
        assert _bucket_key(_prep(populationSize=32)) != base
        assert _bucket_key(_prep(timeLimit=5)) != base
        # program-changing options force the solo path entirely
        assert _bucket_key(_prep(includeStats=True)) is None
        assert _bucket_key(_prep(islands=2)) is None
        assert _bucket_key(_prep(localSearch=True)) is None


class TestBatchSplitMerge:
    def test_batched_results_match_their_own_instances(self):
        """K same-shape instances solved in one vmapped launch: each
        returned tour must visit its OWN instance's customers and price
        to within noise of a solo solve of that instance (tiny instances
        converge to the optimum either way — a cross-instance mixup
        would show up as a wildly wrong cost)."""
        from vrpms_tpu.core import make_instance
        from vrpms_tpu.core.encoding import routes_from_giant
        from vrpms_tpu.sched.batch import solve_sa_batch
        from vrpms_tpu.solvers import SAParams, solve_sa

        rng = np.random.default_rng(3)
        insts = []
        for _ in range(3):
            pts = rng.uniform(0, 100, size=(7, 2))
            d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
            insts.append(
                make_instance(d, demands=[0] + [2] * 6, capacities=[8, 8])
            )
        p = SAParams(n_chains=32, n_iters=400)
        batched = solve_sa_batch(insts, [1, 2, 3], params=p)
        assert len(batched) == 3
        for i, res in enumerate(batched):
            visited = sorted(
                c for r in routes_from_giant(res.giant) for c in r
            )
            assert visited == [1, 2, 3, 4, 5, 6]
            solo = solve_sa(insts[i], key=1, params=p)
            assert float(res.cost) <= float(solo.cost) * 1.1 + 1e-6

    def test_batch_pads_to_power_of_two(self):
        """3 instances pad to 4 internally; the padded clone's result is
        discarded and exactly 3 results come back."""
        from vrpms_tpu.core import make_instance
        from vrpms_tpu.sched.batch import solve_sa_batch
        from vrpms_tpu.solvers import SAParams

        rng = np.random.default_rng(4)
        insts = []
        for _ in range(3):
            pts = rng.uniform(0, 100, size=(6, 2))
            d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
            insts.append(make_instance(d, demands=[0] + [1] * 5,
                                       capacities=[9]))
        res = solve_sa_batch(
            insts, [5, 6, 7], params=SAParams(n_chains=32, n_iters=200)
        )
        assert len(res) == 3
